"""Fused projection + softmax cross-entropy head.

Reference lineage: MXNet's ``SoftmaxOutput`` (``src/operator/
softmax_output.cc``) fuses softmax with its CE gradient so the normalized
probabilities never round-trip through memory. The TPU-native build goes
one step further and folds the VOCAB PROJECTION in too: for an MLM/LM
head, the (N, vocab) logits tensor is the single largest intermediate of
the whole training step (batch 32 x seq 512 x 30k vocab = 1 GB bf16, plus
an f32 softmax-grad sibling and XLA relayout copies — ~6 GB of HBM
traffic measured on BERT-base, PERF.md round 3). This op computes

    loss_i = logsumexp_v(h_i . W_v + b_v) - (h_i . W_label_i + b_label_i)

by scanning over VOCAB CHUNKS with an online (base-2) logsumexp — the
flash-attention trade applied to the classifier: logits chunks live only
in registers/VMEM-scale working sets, and the backward recomputes each
chunk's softmax from the saved per-token logsumexp.

Gradients flow to hidden, weight and bias (dW accumulated chunk-by-chunk
into the full table — parameter-sized, unavoidable and wanted).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as _np

from .registry import register

_LOG2E = _np.float32(1.4426950408889634)
_NEG = _np.float32(-1e30)


def _pad_vocab(weight, bias, chunk):
    v = weight.shape[0]
    v_pad = -(-v // chunk) * chunk
    if v_pad != v:
        weight = jnp.pad(weight, ((0, v_pad - v), (0, 0)))
        # -inf bias on padding rows: exp2 -> 0, never the max for real
        # tokens, and labels < v never pick them
        bias = jnp.concatenate(
            [bias, jnp.full((v_pad - v,), _NEG, bias.dtype)])
    return weight, bias, v_pad


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _fused_ce(hidden, weight, bias, labels, chunk):
    return _fused_ce_fwd(hidden, weight, bias, labels, chunk)[0]


def _chunk_logits(hidden, w_c, b_c, prec):
    s = jax.lax.dot_general(
        hidden, w_c, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=prec)
    if b_c is None:
        return s
    return s + b_c.astype(jnp.float32)[None, :]


def _prec(dtype):
    return (jax.lax.Precision.HIGHEST if jnp.dtype(dtype) == jnp.float32
            else jax.lax.Precision.DEFAULT)


def _fused_ce_fwd(hidden, weight, bias, labels, chunk):
    # weight/bias arrive pre-padded to a chunk multiple (wrapper pads
    # OUTSIDE the custom_vjp so cotangent shapes match the primal and
    # jnp.pad's AD trims the padding grads)
    n, d = hidden.shape
    v_pad = weight.shape[0]
    nc = v_pad // chunk
    w_ch = weight.reshape(nc, chunk, d)
    b_ch = bias.reshape(nc, chunk)
    lab = labels.astype(jnp.int32)
    prec = _prec(hidden.dtype)

    def body(carry, ch):
        m, l, picked = carry
        w_c, b_c, ci = ch
        s2 = _chunk_logits(hidden, w_c, b_c, prec) * _LOG2E   # (N, C) base2
        m_new = jnp.maximum(m, jnp.max(s2, axis=-1))
        l = l * jnp.exp2(m - m_new) + jnp.sum(
            jnp.exp2(s2 - m_new[:, None]), axis=-1)
        # pick the label's logit if it falls in this chunk
        off = lab - ci * chunk
        hit = (off >= 0) & (off < chunk)
        got = jnp.take_along_axis(
            s2, jnp.clip(off, 0, chunk - 1)[:, None], axis=-1)[:, 0]
        picked = jnp.where(hit, got, picked)
        return (m_new, l, picked), None

    m0 = jnp.full((n,), _NEG, jnp.float32)
    l0 = jnp.zeros((n,), jnp.float32)
    p0 = jnp.zeros((n,), jnp.float32)
    # full unroll: ~6 chunks — lets XLA software-pipeline the chunk
    # matmuls instead of serializing through a while loop
    (m, l, picked), _ = jax.lax.scan(
        body, (m0, l0, p0), (w_ch, b_ch, jnp.arange(nc)), unroll=True)
    lse2 = m + jnp.log2(l)
    # back to natural log for the loss value; picked is base-2 scaled
    ln2 = jnp.float32(0.6931471805599453)
    loss = (lse2 - picked) * ln2
    return loss, (hidden, weight, bias, lab, lse2)


def _fused_ce_bwd(chunk, res, g):
    hidden, weight, bias, lab, lse2 = res
    n, d = hidden.shape
    v_pad = weight.shape[0]
    nc = v_pad // chunk
    w_ch = weight.reshape(nc, chunk, d)
    b_ch = bias.reshape(nc, chunk)
    gf = g.astype(jnp.float32)                         # (N,)
    prec = _prec(hidden.dtype)

    def body(carry, ch):
        dx = carry
        w_c, b_c, ci = ch
        s2 = _chunk_logits(hidden, w_c, b_c, prec) * _LOG2E
        p = jnp.exp2(s2 - lse2[:, None])               # softmax chunk (N, C)
        off = lab - ci * chunk
        hit = (off >= 0) & (off < chunk)
        onehot = (jnp.arange(chunk)[None, :] ==
                  jnp.clip(off, 0, chunk - 1)[:, None]) & hit[:, None]
        gl = (p - onehot.astype(jnp.float32)) * gf[:, None]  # dlogits (N, C)
        gl_cast = gl.astype(hidden.dtype)
        dx = dx + jax.lax.dot_general(
            gl_cast, w_c, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)
        dw_c = jax.lax.dot_general(
            gl_cast, hidden, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)  # (C, D)
        db_c = jnp.sum(gl, axis=0)
        return dx, (dw_c, db_c)

    dx0 = jnp.zeros((n, d), jnp.float32)
    dx, (dw_ch, db_ch) = jax.lax.scan(
        body, dx0, (w_ch, b_ch, jnp.arange(nc)), unroll=True)
    dw = dw_ch.reshape(v_pad, d)
    db = db_ch.reshape(v_pad)
    return (dx.astype(hidden.dtype), dw.astype(weight.dtype),
            db.astype(bias.dtype),
            _np.zeros(lab.shape, jax.dtypes.float0))


_fused_ce.defvjp(_fused_ce_fwd, _fused_ce_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _fused_ce_nobias(hidden, weight, labels, chunk):
    return _fused_ce_nobias_fwd(hidden, weight, labels, chunk)[0]


def _fused_ce_nobias_fwd(hidden, weight, labels, chunk):
    """Bias-free head (Llama lm_head): no bias add in the chunk logits,
    no vocab-sized bias cotangent computed-and-discarded each step. The
    padded rows rely on masking: padding can only win the row max when
    EVERY real logit is below 0, so the pad chunks mask to -inf
    explicitly via the vocab validity bound carried in `chunk` math."""
    n, d = hidden.shape
    v_pad = weight.shape[0]
    nc = v_pad // chunk
    w_ch = weight.reshape(nc, chunk, d)
    lab = labels.astype(jnp.int32)
    prec = _prec(hidden.dtype)

    def body(carry, ch):
        m, l, picked = carry
        w_c, ci = ch
        s2 = _chunk_logits(hidden, w_c, None, prec) * _LOG2E
        m_new = jnp.maximum(m, jnp.max(s2, axis=-1))
        l = l * jnp.exp2(m - m_new) + jnp.sum(
            jnp.exp2(s2 - m_new[:, None]), axis=-1)
        off = lab - ci * chunk
        hit = (off >= 0) & (off < chunk)
        got = jnp.take_along_axis(
            s2, jnp.clip(off, 0, chunk - 1)[:, None], axis=-1)[:, 0]
        picked = jnp.where(hit, got, picked)
        return (m_new, l, picked), None

    m0 = jnp.full((n,), _NEG, jnp.float32)
    l0 = jnp.zeros((n,), jnp.float32)
    p0 = jnp.zeros((n,), jnp.float32)
    (m, l, picked), _ = jax.lax.scan(
        body, (m0, l0, p0), (w_ch, jnp.arange(nc)), unroll=True)
    lse2 = m + jnp.log2(l)
    ln2 = jnp.float32(0.6931471805599453)
    return (lse2 - picked) * ln2, (hidden, weight, lab, lse2)


def _fused_ce_nobias_bwd(chunk, res, g):
    hidden, weight, lab, lse2 = res
    n, d = hidden.shape
    v_pad = weight.shape[0]
    nc = v_pad // chunk
    w_ch = weight.reshape(nc, chunk, d)
    gf = g.astype(jnp.float32)
    prec = _prec(hidden.dtype)

    def body(carry, ch):
        dx = carry
        w_c, ci = ch
        s2 = _chunk_logits(hidden, w_c, None, prec) * _LOG2E
        p = jnp.exp2(s2 - lse2[:, None])
        off = lab - ci * chunk
        hit = (off >= 0) & (off < chunk)
        onehot = (jnp.arange(chunk)[None, :] ==
                  jnp.clip(off, 0, chunk - 1)[:, None]) & hit[:, None]
        gl = (p - onehot.astype(jnp.float32)) * gf[:, None]
        gl_cast = gl.astype(hidden.dtype)
        dx = dx + jax.lax.dot_general(
            gl_cast, w_c, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)
        dw_c = jax.lax.dot_general(
            gl_cast, hidden, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)
        return dx, dw_c

    dx0 = jnp.zeros((n, d), jnp.float32)
    dx, dw_ch = jax.lax.scan(body, dx0, (w_ch, jnp.arange(nc)),
                             unroll=True)
    return (dx.astype(hidden.dtype),
            dw_ch.reshape(v_pad, d).astype(weight.dtype),
            _np.zeros(lab.shape, jax.dtypes.float0))


_fused_ce_nobias.defvjp(_fused_ce_nobias_fwd, _fused_ce_nobias_bwd)


@register("_contrib_softmax_ce_head", aliases=["softmax_ce_head"])
def softmax_ce_head(hidden, weight, bias=None, labels=None, *, chunk=5120):
    """Per-position CE loss of a tied/untied vocab projection, computed
    WITHOUT materializing the (N, vocab) logits (see module docstring).

    hidden (..., D); weight (V, D); bias (V,) or None (bias-free heads
    pay no vocab-sized bias-grad sweep); labels (...) int.
    Returns per-position loss shaped like ``labels`` (f32).
    """
    lead = hidden.shape[:-1]
    d = hidden.shape[-1]
    h2 = hidden.reshape(-1, d)
    lab = labels.reshape(-1)
    chunk = int(chunk)
    if bias is None:
        v = weight.shape[0]
        v_pad = -(-v // chunk) * chunk
        if v_pad != v:
            # no bias to carry the -inf mask: guard padded rows by
            # padding labels-space weights with zeros AND masking via a
            # -inf bias chunk would reintroduce the bias — instead pad
            # and rely on the loss being exact only over real rows:
            # zero-padded rows contribute exp(h.0)=1 terms, so pad must
            # be masked. Fall back to the bias variant with a zero bias
            # ONLY for the padded tail case.
            w_p, b_p, _ = _pad_vocab(
                weight, jnp.zeros((v,), jnp.float32), chunk)
            loss = _fused_ce(h2, w_p, b_p, lab, chunk)
            return loss.reshape(lead)
        loss = _fused_ce_nobias(h2, weight, lab, chunk)
        return loss.reshape(lead)
    weight, bias, _ = _pad_vocab(weight, bias, chunk)
    loss = _fused_ce(h2, weight, bias, lab, chunk)
    return loss.reshape(lead)
