"""Fused attention ops.

Reference: ``src/operator/contrib/transformer.cc`` — MXNet's fused attention
is a pair of batched-matmul kernels (`_contrib_interleaved_matmul_selfatt_qk`
/ `..._valatt`) used by GluonNLP's Transformer/BERT. The TPU-native design
exposes ONE fused scaled-dot-product attention op instead: softmax statistics
in f32, bf16 matmuls on the MXU, and a single seam where the Pallas
flash-attention kernel (mxnet_tpu.pallas_kernels) replaces the reference
path on TPU for long sequences.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .registry import register


def _sdpa_reference(q, k, v, mask, scale, causal, layout="bhld",
                    dropout=0.0, seed=None):
    """f32-softmax attention. layout "bhld": (B, H, L, D); "blhd":
    (B, L, H, D) — head transposes fold into the einsum contractions.

    ``dropout``: attention-probability dropout using the SAME stateless
    position-hash mask as the Pallas flash kernels (bitwise identical
    given the same seed) — this path is the kernels' dense oracle."""
    dtype = q.dtype
    if layout == "blhd":
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k)
    else:
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k)
    scores = scores.astype(jnp.float32) * scale
    if causal:
        lq, lk = scores.shape[-2], scores.shape[-1]
        causal_mask = jnp.tril(jnp.ones((lq, lk), dtype=bool), k=lk - lq)
        scores = jnp.where(causal_mask, scores, jnp.float32(-1e9))
    if mask is not None:
        # mask: 1 = attend, 0 = ignore; broadcastable to (B, H, Lq, Lk)
        m = jnp.broadcast_to(mask.astype(bool), scores.shape)
        scores = jnp.where(m, scores, jnp.float32(-1e9))
    probs = jax.nn.softmax(scores, axis=-1)
    if dropout > 0.0:
        from ..pallas_kernels.flash_attention import (_drop_mask,
                                                      dropout_thresh)

        b, h, lq, lk = probs.shape
        shp = probs.shape
        head = (jax.lax.broadcasted_iota(jnp.int32, shp, 0) * h
                + jax.lax.broadcasted_iota(jnp.int32, shp, 1))
        qp = jax.lax.broadcasted_iota(jnp.int32, shp, 2)
        kp = jax.lax.broadcasted_iota(jnp.int32, shp, 3)
        keep = _drop_mask(head, qp, kp, lq, lk,
                          jnp.asarray(seed, jnp.uint32).reshape(-1)[0],
                          dropout_thresh(float(dropout)))
        probs = jnp.where(keep,
                          probs * jnp.float32(1.0 / (1.0 - dropout)), 0.0)
    probs = probs.astype(dtype)
    if layout == "blhd":
        return jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


@register("_contrib_sdp_attention", aliases=["sdp_attention"],
          needs_rng=True, pass_training_flag=True,
          rng_gate=lambda attrs: bool(attrs.get("dropout"))
          and bool(attrs.get("_training")))
def sdp_attention(rng, query, key, value, mask=None, *, scale=None,
                  causal=False, flash=True, layout="bhld", ring_axis=None,
                  dropout=0.0, _training=False):
    """Scaled dot-product attention.

    ``layout``: "bhld" (batch, heads, seq, head_dim) or "blhd" (batch, seq,
    heads, head_dim). blhd runs the XLA einsum path (head transposes fold
    into the contractions); the Pallas kernel currently takes bhld only —
    Mosaic cannot tile a per-head (seq, head_dim) block of a blhd array
    (squeezed H lands in sublane position), see flash_shape_supported.

    ``flash=True`` routes to the Pallas flash kernel on TPU when the shape
    qualifies (seq multiple of block size); otherwise the XLA reference path
    runs (which XLA fuses well on its own for short sequences).

    ``dropout``: attention-probability dropout (reference capability:
    GluonNLP MultiHeadAttentionCell applies dropout to the attention
    weights). Training-mode only. Generated INSIDE the flash kernels from
    a stateless position hash (pallas_kernels.flash_attention._drop_mask)
    seeded from this op's PRNG key; the reference/scan paths use the
    bitwise-identical mask, so every dispatch route drops the same
    elements for a given key.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(query.shape[-1])
    p_drop = float(dropout) if _training else 0.0
    seed = None
    if p_drop > 0.0:
        from ..pallas_kernels.flash_attention import fold_key_seed

        seed = fold_key_seed(rng)
    from ..parallel.ring_attention import ring_active

    if ring_axis is not None and mask is None and ring_active(ring_axis):
        # sequence-parallel exact attention over the mesh ring; when no
        # mesh/axis is active we fall through to the normal flash/
        # reference dispatch below instead of pinning the dense path
        from ..parallel.ring_attention import ring_attention

        if p_drop > 0.0:
            raise ValueError(
                "sdp_attention: attention dropout is not supported with "
                "ring (sequence-parallel) attention — the per-pair mask "
                "would need globally-consistent positions across shards")
        if layout == "blhd":
            out = ring_attention(query.transpose(0, 2, 1, 3),
                                 key.transpose(0, 2, 1, 3),
                                 value.transpose(0, 2, 1, 3),
                                 axis=ring_axis, causal=causal, scale=scale)
            return out.transpose(0, 2, 1, 3)
        return ring_attention(query, key, value, axis=ring_axis,
                              causal=causal, scale=scale)
    if flash and mask is None:
        from ..pallas_kernels import (flash_attention, flash_attention_scan,
                                      flash_supported)

        if flash_supported(query, key, value, causal=causal, layout=layout):
            return flash_attention(query, key, value, scale=scale,
                                   causal=causal, layout=layout,
                                   dropout=p_drop, seed=seed)
        seq_ax = 1 if layout == "blhd" else -2
        if key.shape[seq_ax] >= 2048:
            # long sequence off-TPU: O(L) memory blockwise path
            if layout == "blhd":
                out = flash_attention_scan(
                    query.transpose(0, 2, 1, 3), key.transpose(0, 2, 1, 3),
                    value.transpose(0, 2, 1, 3), scale=scale, causal=causal,
                    dropout=p_drop, seed=seed)
                return out.transpose(0, 2, 1, 3)
            return flash_attention_scan(query, key, value, scale=scale,
                                        causal=causal, dropout=p_drop,
                                        seed=seed)
    return _sdpa_reference(query, key, value, mask, scale, causal,
                           layout=layout, dropout=p_drop, seed=seed)


@register("_contrib_rms_norm", aliases=["rms_norm"])
def rms_norm(data, weight, *, eps=1e-6):
    """RMSNorm (no reference counterpart — Llama-era op, SURVEY.md §5.7).
    Statistics in f32, output in compute dtype. Under
    ``MXNET_PALLAS_FUSED=1`` + shape/platform gates the Pallas one-pass
    kernel takes it (pallas_kernels/fused_layers.py, RMS mode): the
    Llama blocks adopt the fused-layer path through this seam without
    any model change."""
    from ..pallas_kernels.fused_layers import (fused_layers_enabled,
                                               fused_ln_supported)

    if fused_layers_enabled() and fused_ln_supported(data):
        from .. import telemetry
        from ..pallas_kernels.fused_layers import fused_rms_norm

        telemetry.record_pallas_dispatch("fused_rms_norm")
        return fused_rms_norm(data, weight, eps=eps)
    x32 = data.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * inv).astype(data.dtype) * weight


def _paged_reference(q, k_arena, v_arena, page_table, lengths,
                     q_positions, page_size, scale):
    """Eager paged attention: gather K/V rows through the page table,
    then masked f32-softmax attention. The CPU oracle for the Pallas
    paged kernel, and the decode path everywhere off-TPU."""
    b, h, lq, d = q.shape
    kv = k_arena.shape[-2]
    ps = int(page_size)
    # flat slot indices for every token position the tables can reach:
    # token i of row b lives at page_table[b, i//ps]*ps + i%ps
    slots = (page_table[:, :, None] * ps
             + jnp.arange(ps, dtype=page_table.dtype)[None, None, :])
    slots = slots.reshape(b, -1)                        # (B, T)
    k = jnp.take(k_arena, slots, axis=0)                # (B, T, KV, D)
    v = jnp.take(v_arena, slots, axis=0)
    if kv != h:
        rep = h // kv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    k = k.transpose(0, 2, 1, 3)                         # (B, H, T, D)
    v = v.transpose(0, 2, 1, 3)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k)
    scores = scores.astype(jnp.float32) * scale
    key_pos = jnp.arange(slots.shape[1], dtype=jnp.int32)
    # causal over the request's own timeline: key position <= query
    # position (which is <= length-1 for every real row). A padding row
    # (length 0, position 0) sees only scratch key 0 — garbage, sliced
    # away by the batcher before any caller looks.
    mask = key_pos[None, None, None, :] <= \
        q_positions[:, None, :, None]
    mask = mask & (key_pos[None, None, None, :]
                   < lengths[:, None, None, None])
    scores = jnp.where(mask, scores, jnp.float32(-1e9))
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


@register("_contrib_paged_attention", aliases=["paged_attention"])
def paged_attention(query, k_arena, v_arena, page_table, lengths,
                    q_positions=None, *, page_size, scale=None):
    """Attention over a paged KV cache (serving decode path).

    ``query``: (B, H, Lq, D); ``k_arena``/``v_arena``: (slots, KV, D) —
    ONE layer's arena from :func:`mxnet_tpu.serving.kvcache.make_kv_arena`;
    ``page_table``: (B, P) int32 page ids (scratch page 0 pads the
    tail); ``lengths``: (B,) int32 tokens valid per row INCLUDING the
    current query tokens; ``q_positions``: (B, Lq) absolute positions of
    the query rows (default: the trailing positions, i.e.
    ``lengths - Lq + arange(Lq)`` — the decode/prefill common case).

    Under ``MXNET_PALLAS_FUSED=1`` the single-query decode shape routes
    to the Pallas paged kernel on TPU when eligible
    (pallas_kernels/paged_attention.py); everything else runs the eager
    gather, which doubles as the kernel's bit-oracle.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(query.shape[-1])
    lq = query.shape[2]
    if q_positions is None:
        q_positions = (lengths[:, None] - lq
                       + jnp.arange(lq, dtype=lengths.dtype)[None, :])
    from ..pallas_kernels.fused_layers import fused_layers_enabled
    from ..pallas_kernels.paged_attention import (paged_attention_kernel,
                                                  paged_supported)

    if lq == 1 and fused_layers_enabled() \
            and paged_supported(query, k_arena, page_size):
        from .. import telemetry

        telemetry.record_pallas_dispatch("paged_attention")
        return paged_attention_kernel(query, k_arena, v_arena,
                                      page_table, lengths,
                                      page_size=page_size, scale=scale)
    return _paged_reference(query, k_arena, v_arena, page_table, lengths,
                            q_positions, page_size, scale)


def rope_at(data, positions, *, theta=10000.0, interleaved=False):
    """:func:`rope` with explicit per-row absolute positions —
    ``positions`` (B, L) int — the decode-step form, where every row of
    the batch sits at a different depth of its own sequence. Bitwise
    identical to :func:`rope` when
    ``positions == offset + arange(L)`` broadcast over the batch (the
    cos/sin tables are built from positions the same way)."""
    b, l, h, d = data.shape
    pos = positions.astype(jnp.float32)                  # (B, L)
    inv_freq = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = pos[:, :, None] * inv_freq[None, None, :]   # (B, L, D/2)
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    if interleaved:
        x1 = data[..., 0::2].astype(jnp.float32)
        x2 = data[..., 1::2].astype(jnp.float32)
    else:
        x1 = data[..., : d // 2].astype(jnp.float32)
        x2 = data[..., d // 2:].astype(jnp.float32)
    r1 = x1 * cos - x2 * sin
    r2 = x2 * cos + x1 * sin
    if interleaved:
        out = jnp.stack([r1, r2], axis=-1).reshape((b, l, h, d))
    else:
        out = jnp.concatenate([r1, r2], axis=-1)
    return out.astype(data.dtype)


@register("_contrib_rope", aliases=["rope"])
def rope(data, *, theta=10000.0, position_offset=0, interleaved=False):
    """Rotary position embedding over (B, L, H, D).

    Default is the true rotate-half convention (Llama / HF checkpoints):
    the head dim is split into first/second halves and rotated as
    ``concat(x1*cos - x2*sin, x2*cos + x1*sin)``, so weights ported from
    Llama-family checkpoints produce identical activations.
    ``interleaved=True`` selects the GPT-J/NeoX even-odd pair convention.
    Computed in-graph from positions — no host-side tables."""
    b, l, h, d = data.shape
    pos = jnp.arange(position_offset, position_offset + l,
                     dtype=jnp.float32)
    inv_freq = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = pos[:, None] * inv_freq[None, :]            # (L, D/2)
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    if interleaved:
        x1 = data[..., 0::2].astype(jnp.float32)
        x2 = data[..., 1::2].astype(jnp.float32)
    else:
        x1 = data[..., : d // 2].astype(jnp.float32)
        x2 = data[..., d // 2:].astype(jnp.float32)
    r1 = x1 * cos - x2 * sin
    r2 = x2 * cos + x1 * sin
    if interleaved:
        out = jnp.stack([r1, r2], axis=-1).reshape((b, l, h, d))
    else:
        out = jnp.concatenate([r1, r2], axis=-1)
    return out.astype(data.dtype)
