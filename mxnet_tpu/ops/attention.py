"""Fused attention ops.

Reference: ``src/operator/contrib/transformer.cc`` — MXNet's fused attention
is a pair of batched-matmul kernels (`_contrib_interleaved_matmul_selfatt_qk`
/ `..._valatt`) used by GluonNLP's Transformer/BERT. The TPU-native design
exposes ONE fused scaled-dot-product attention op instead: softmax statistics
in f32, bf16 matmuls on the MXU, and a single seam where the Pallas
flash-attention kernel (mxnet_tpu.pallas_kernels) replaces the reference
path on TPU for long sequences.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .registry import register


def _sdpa_reference(q, k, v, mask, scale, causal):
    """(B, H, Lq, D) x (B, H, Lk, D) -> (B, H, Lq, D); f32 softmax."""
    dtype = q.dtype
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        lq, lk = scores.shape[-2], scores.shape[-1]
        causal_mask = jnp.tril(jnp.ones((lq, lk), dtype=bool), k=lk - lq)
        scores = jnp.where(causal_mask, scores, jnp.float32(-1e9))
    if mask is not None:
        # mask: 1 = attend, 0 = ignore; broadcastable to (B, H, Lq, Lk)
        m = jnp.broadcast_to(mask.astype(bool), scores.shape)
        scores = jnp.where(m, scores, jnp.float32(-1e9))
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(dtype), v)


@register("_contrib_sdp_attention", aliases=["sdp_attention"])
def sdp_attention(query, key, value, mask=None, *, scale=None, causal=False,
                  flash=True):
    """Scaled dot-product attention over (batch, heads, seq, head_dim).

    ``flash=True`` routes to the Pallas flash kernel on TPU when the shape
    qualifies (seq multiple of block size); otherwise the XLA reference path
    runs (which XLA fuses well on its own for short sequences).
    """
    if scale is None:
        scale = 1.0 / math.sqrt(query.shape[-1])
    if flash and mask is None:
        from ..pallas_kernels import (flash_attention, flash_attention_scan,
                                      flash_supported)

        if flash_supported(query, key, value, causal=causal):
            return flash_attention(query, key, value, scale=scale,
                                   causal=causal)
        if key.shape[-2] >= 2048:
            # long sequence off-TPU: O(L) memory blockwise path
            return flash_attention_scan(query, key, value, scale=scale,
                                        causal=causal)
    return _sdpa_reference(query, key, value, mask, scale, causal)


@register("_contrib_rms_norm", aliases=["rms_norm"])
def rms_norm(data, weight, *, eps=1e-6):
    """RMSNorm (no reference counterpart — Llama-era op, SURVEY.md §5.7).
    Statistics in f32, output in compute dtype."""
    x32 = data.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * inv).astype(data.dtype) * weight


@register("_contrib_rope", aliases=["rope"])
def rope(data, *, theta=10000.0, position_offset=0, interleaved=False):
    """Rotary position embedding over (B, L, H, D).

    Default is the true rotate-half convention (Llama / HF checkpoints):
    the head dim is split into first/second halves and rotated as
    ``concat(x1*cos - x2*sin, x2*cos + x1*sin)``, so weights ported from
    Llama-family checkpoints produce identical activations.
    ``interleaved=True`` selects the GPT-J/NeoX even-odd pair convention.
    Computed in-graph from positions — no host-side tables."""
    b, l, h, d = data.shape
    pos = jnp.arange(position_offset, position_offset + l,
                     dtype=jnp.float32)
    inv_freq = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = pos[:, None] * inv_freq[None, :]            # (L, D/2)
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    if interleaved:
        x1 = data[..., 0::2].astype(jnp.float32)
        x2 = data[..., 1::2].astype(jnp.float32)
    else:
        x1 = data[..., : d // 2].astype(jnp.float32)
        x2 = data[..., d // 2:].astype(jnp.float32)
    r1 = x1 * cos - x2 * sin
    r2 = x2 * cos + x1 * sin
    if interleaved:
        out = jnp.stack([r1, r2], axis=-1).reshape((b, l, h, d))
    else:
        out = jnp.concatenate([r1, r2], axis=-1)
    return out.astype(data.dtype)
