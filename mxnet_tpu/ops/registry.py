"""Operator registry.

Reference: the nnvm op registry (``3rdparty/tvm/nnvm/include/nnvm/op.h``)
plus MXNet's per-op registration pattern
(``src/operator/... :: NNVM_REGISTER_OP(x).set_attr<FCompute>(...)``).

In the TPU-native build an operator is a **pure JAX function**
``fn(*tensors, **attrs) -> array | tuple`` registered by its MXNet name.
The same registry serves:

* the imperative frontend (``mx.nd.*`` wrappers dispatch here, with an
  eager per-op executable cache — the equivalent of MXNet pushing one op
  to the ThreadedEngine, see §7.3.2 of SURVEY.md);
* the symbolic frontend (``mx.sym.*`` records the op name + attrs into a
  graph; the Executor looks implementations up here at jit time);
* autograd (``jax.vjp`` over the pure function replaces per-op FGradient
  attrs — XLA derives the backward, no hand-written grads needed except
  where MXNet defines *non-mathematical* gradients, e.g. SoftmaxOutput,
  which use ``jax.custom_vjp`` in their impl).

Attr convention: tensor inputs are positional parameters; attributes are
keyword(-only) parameters with defaults. The wrapper generators use
``inspect`` to split the two.
"""
from __future__ import annotations

import functools
import inspect
import threading
import time
from typing import Callable, Dict, List, NamedTuple, Optional, Sequence

from .. import engine, fault, telemetry
from ..fault import _state as _fault_state
from ..telemetry import _state as _telemetry_state

__all__ = ["OpDef", "AttrSpec", "attr", "register", "get_op", "list_ops",
           "alias", "validate_attrs", "execute_segment",
           "fused_segment_cache_clear"]


class AttrSpec(NamedTuple):
    """Typed operator-attribute declaration.

    The dmlc::Parameter equivalent (reference: ``include/dmlc/parameter.h``
    — typed param structs with range checks whose descriptions flow into
    the generated op docs). Declared per-op at ``register(attrs=[...])``;
    validated on every call; rendered into the ``mx.nd.*`` / ``mx.sym.*``
    wrapper docstrings.
    """

    name: str
    type: object = None          # python type or tuple of types
    doc: str = ""
    low: Optional[float] = None  # inclusive numeric bounds
    high: Optional[float] = None
    choices: Optional[tuple] = None

    def describe(self):
        parts = []
        if self.type is not None:
            ts = self.type if isinstance(self.type, tuple) else (self.type,)
            parts.append("/".join(t.__name__ for t in ts))
        if self.choices is not None:
            parts.append("one of " + ", ".join(map(repr, self.choices)))
        if self.low is not None or self.high is not None:
            lo = "-inf" if self.low is None else self.low
            hi = "inf" if self.high is None else self.high
            parts.append(f"range [{lo}, {hi}]")
        return ", ".join(parts)


def attr(name, type=None, doc="", low=None, high=None, choices=None):
    return AttrSpec(name, type, doc, low, high,
                    tuple(choices) if choices is not None else None)


_COERCIBLE = {
    int: (int,),
    float: (int, float),
    bool: (bool, int),
    str: (str,),
    tuple: (tuple, list, int),
}


def validate_attrs(opdef: "OpDef", attrs: Dict) -> None:
    """Raise a typed MXNetError naming the op, attribute and constraint
    for out-of-spec attribute values. Undeclared attributes pass (specs
    cover the documented surface, not every internal knob)."""
    specs = opdef.attr_specs
    if not specs:
        return
    from ..base import MXNetError

    import numpy as _np

    for k, v in attrs.items():
        spec = specs.get(k)
        if spec is None or v is None:
            continue
        if isinstance(v, (_np.generic,)):
            v = v.item()
        if spec.type is not None:
            want = spec.type if isinstance(spec.type, tuple) else (spec.type,)
            ok = any(isinstance(v, _COERCIBLE.get(t, (t,))) for t in want)
            # bools are ints in python — reject bool where int expected
            if ok and bool not in want and isinstance(v, bool):
                ok = False
            if not ok:
                raise MXNetError(
                    f"{opdef.name}: attribute {k}={v!r} has type "
                    f"{type(v).__name__}; expected {spec.describe()}")
        if spec.choices is not None and v not in spec.choices:
            raise MXNetError(
                f"{opdef.name}: attribute {k}={v!r} must be "
                f"{spec.describe()}")
        vals = v if isinstance(v, (tuple, list)) else (v,)
        for item in vals:
            if not isinstance(item, (int, float)) or isinstance(item, bool):
                continue
            if spec.low is not None and item < spec.low:
                raise MXNetError(
                    f"{opdef.name}: attribute {k}={v!r} below "
                    f"{spec.describe()}")
            if spec.high is not None and item > spec.high:
                raise MXNetError(
                    f"{opdef.name}: attribute {k}={v!r} above "
                    f"{spec.describe()}")


def render_attr_docs(opdef: "OpDef") -> str:
    """Numpy-style attribute section for generated wrapper docstrings."""
    if not opdef.attr_specs:
        return ""
    lines = ["", "", "Attributes", "----------"]
    for spec in opdef.attr_specs.values():
        head = spec.name
        desc = spec.describe()
        if desc:
            head += f" : {desc}"
        lines.append(head)
        if spec.doc:
            lines.append(f"    {spec.doc}")
    return "\n".join(lines)


class OpDef(NamedTuple):
    name: str
    fn: Callable
    # names of tensor (array) parameters, in order
    tensor_params: tuple
    # tensor params that may be None (optional inputs like bias)
    optional_tensor_params: frozenset
    # attr param names
    attr_params: tuple
    # whether the fn consumes a PRNG key as first argument (random ops)
    needs_rng: bool
    # number of outputs; None = infer from returned tuple
    num_outputs: Optional[int]
    # if True, the imperative wrapper resolves autograd.is_training() and
    # passes it as the `_training` attr
    pass_training_flag: bool
    # accepts variable number of tensor inputs as a leading list
    variadic: bool
    # op must run untraced (dynamic output shapes — e.g. boolean_mask)
    eager_only: bool
    # typed attribute declarations (AttrSpec by name); None = undeclared
    attr_specs: Optional[Dict] = None
    # fn has **kwargs: forward ALL attrs, not just declared attr_params
    # (the `Custom` op's user-defined attribute surface)
    var_attrs: bool = False
    # optional attrs -> bool predicate: draw/consume a PRNG key only when
    # it returns True (ops like sdp_attention that are random only when a
    # dropout attr is set — an unconditional draw would advance the
    # global stream on every eval-mode call, a reproducibility trap).
    # When gated off the fn still receives rng=None positionally.
    rng_gate: Optional[Callable] = None


_REGISTRY: Dict[str, OpDef] = {}


def register(
    name: Optional[str] = None,
    aliases: Sequence[str] = (),
    needs_rng: bool = False,
    num_outputs: Optional[int] = None,
    pass_training_flag: bool = False,
    variadic: bool = False,
    eager_only: bool = False,
    attrs: Sequence[AttrSpec] = (),
    rng_gate: Optional[Callable] = None,
):
    """Decorator registering a pure-JAX op implementation.

    ``attrs``: optional typed AttrSpec declarations (the dmlc::Parameter
    equivalent) — validated on every call, rendered into wrapper docs.
    """

    def deco(fn):
        opname = name or fn.__name__
        sig = inspect.signature(fn)
        tensor_params: List[str] = []
        optional: List[str] = []
        attr_params: List[str] = []
        for pname, p in sig.parameters.items():
            if needs_rng and pname == "rng":
                continue
            if pname == "_training":
                continue
            if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
                if p.kind == p.POSITIONAL_OR_KEYWORD and p.default is not inspect.Parameter.empty and not _is_tensor_default(p.default):
                    attr_params.append(pname)
                else:
                    tensor_params.append(pname)
                    if p.default is None:
                        optional.append(pname)
            elif p.kind == p.KEYWORD_ONLY:
                attr_params.append(pname)
            elif p.kind == p.VAR_POSITIONAL:
                # variadic tensor inputs (e.g. Concat, add_n)
                tensor_params.append(pname)
        opdef = OpDef(
            name=opname,
            fn=fn,
            tensor_params=tuple(tensor_params),
            optional_tensor_params=frozenset(optional),
            attr_params=tuple(attr_params),
            needs_rng=needs_rng,
            num_outputs=num_outputs,
            pass_training_flag=pass_training_flag,
            variadic=variadic or any(
                p.kind == p.VAR_POSITIONAL for p in sig.parameters.values()
            ),
            eager_only=eager_only,
            attr_specs={s.name: s for s in attrs} if attrs else None,
            var_attrs=any(p.kind == p.VAR_KEYWORD
                          for p in sig.parameters.values()),
            rng_gate=rng_gate,
        )
        _REGISTRY[opname] = opdef
        for a in aliases:
            _REGISTRY[a] = opdef
        fn.__opdef__ = opdef
        return fn

    return deco


def _is_tensor_default(default):
    # positional params whose default is None are optional tensors (bias=None)
    return default is None


def alias(new_name: str, existing: str) -> None:
    _REGISTRY[new_name] = _REGISTRY[existing]


def get_op(name: str) -> OpDef:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise NotImplementedError(
            f"operator {name!r} is not implemented in mxnet_tpu "
            f"(see SURVEY.md §2.1 op families for the porting roadmap)"
        ) from None


def has_op(name: str) -> bool:
    return name in _REGISTRY


def list_ops() -> List[str]:
    return sorted(_REGISTRY)


# ---------------------------------------------------------------------------
# Eager single-op executable cache.
#
# Reference analogue: MXNet's imperative path pays ~µs dispatch per op
# (SURVEY.md §3.1); ours pays a jit-cache lookup. Executables are cached by
# (op name, attr values); XLA itself caches by input shape/dtype underneath.
# Routed through the compilation service (compiler.SiteCache): one
# canonical keying scheme, LRU policy preserved, evictions observable.
# ---------------------------------------------------------------------------

from ..compiler import keys as _ckeys
from ..compiler import manifest as _cmanifest

# canonical name kept: block.py / step.py key their caches with the same
# knobs (the compilation service owns the definition now)
_routing_knobs = _ckeys.routing_knobs

_EAGER_CACHE = None


def _eager_cache():
    global _EAGER_CACHE
    if _EAGER_CACHE is None:
        from ..compiler import service as _csvc

        _EAGER_CACHE = _csvc.shared_cache("eager_op", maxsize=4096)
    return _EAGER_CACHE


def _build_eager(opname: str, attr_items: tuple, has_rng: bool):
    # `platform` keys the cache even though the traced fn only reads it
    # ambiently: op impls dispatch on current_execution_platform() at
    # TRACE time (Pallas kernels, int8 MXU paths), so one executable per
    # platform — otherwise the first-traced platform's body would be
    # served everywhere (round-3 review finding, verified live)
    import jax

    opdef = _REGISTRY[opname]
    attrs = dict(attr_items)

    if has_rng:
        def pure(rng, *tensors):
            return opdef.fn(rng, *tensors, **attrs)
    elif opdef.needs_rng:
        # rng draw gated off (rng_gate): the fn still expects the slot
        def pure(*tensors):
            return opdef.fn(None, *tensors, **attrs)
    else:
        def pure(*tensors):
            return opdef.fn(*tensors, **attrs)

    pure.__name__ = opname
    return jax.jit(pure)


def _eager_executable(opname: str, attr_items: tuple, n_tensors: int,
                      has_rng: bool, platform: str, routing: tuple = (),
                      record: bool = True):
    """(jitted fn, cache hit) through the service's eager_op site cache."""
    cache = _eager_cache()
    key = _ckeys.signature("eager_op", opname, attrs=attr_items,
                           platform=platform, routing=routing,
                           extra=(n_tensors, has_rng))
    fn = cache.lookup(key, record=record)
    if fn is not cache.MISS:
        return fn, True
    fn = _build_eager(opname, attr_items, has_rng)
    cache.insert(key, fn)
    return fn, False


def _cached_call(opname: str, attr_items: tuple, n_tensors: int,
                 has_rng: bool, platform: str, routing: tuple = ()):
    """Compat shim over the service cache (amp and tests call this
    directly); telemetry-silent — the dispatch path records through
    :func:`_eager_executable`."""
    return _eager_executable(opname, attr_items, n_tensors, has_rng,
                             platform, routing, record=False)[0]


def _cached_call_clear():
    _eager_cache().clear()


_cached_call.cache_clear = _cached_call_clear


def _harmonize_devices(tensors):
    """Mixed single-device / mesh-sharded operands: replicate the
    single-device ones onto the sharded operand's mesh.

    This is what lets a model trained by parallel.TrainStep (params laid out
    over a Mesh) be used eagerly afterwards — ``net(x)`` with a host-side
    ``x`` — without the user re-placing anything. The reference's analogue
    is ``as_in_context`` coercion; here the "context" is the mesh layout.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    # In-trace operands are tracers on one logical device set already; and
    # Tracer.sharding raises an AttributeError whose MESSAGE construction
    # walks the whole jaxpr for provenance — profiled at ~70% of total
    # model trace time when this ran per-op (see PERF.md round 3).
    for t in tensors:
        if isinstance(t, jax.core.Tracer):
            return tensors

    mesh = None
    mixed = False
    for t in tensors:
        sh = getattr(t, "sharding", None)
        if isinstance(sh, NamedSharding) and sh.num_devices > 1:
            if mesh is None:
                mesh = sh.mesh
        elif hasattr(t, "sharding"):
            mixed = True
    if mesh is None or not mixed:
        return tensors
    rep = NamedSharding(mesh, PartitionSpec())
    out = []
    for t in tensors:
        sh = getattr(t, "sharding", None)
        if isinstance(sh, NamedSharding) and sh.num_devices > 1:
            out.append(t)
        else:
            out.append(jax.device_put(t, rep))
    return type(tensors)(out) if isinstance(tensors, tuple) else out


def eager_call(opdef: OpDef, tensors, attrs, rng=None):
    """Execute an op eagerly through the per-op executable cache.

    Telemetry (MXNET_TELEMETRY=1): per-op invocation count + host dispatch
    latency; disabled mode costs exactly this one branch. Fault site
    ``engine.dispatch`` (MXNET_FAULT_SPEC): one injection opportunity per
    dispatch — errors here propagate like a failed device op (the
    ThreadedVar ExceptionRef analogue); likewise one branch when off.
    """
    if _fault_state.enabled:
        fault.check("engine.dispatch", opdef.name)
    if _telemetry_state.enabled:
        t0 = time.perf_counter()
        try:
            return _eager_call(opdef, tensors, attrs, rng)
        finally:
            telemetry.record_op_dispatch(
                opdef.name, time.perf_counter() - t0)
    return _eager_call(opdef, tensors, attrs, rng)


def _eager_call(opdef: OpDef, tensors, attrs, rng=None):
    from ..base import current_execution_platform, execution_platform

    if opdef.attr_specs:
        validate_attrs(opdef, attrs)
    scope = engine.current_bulk_scope()
    if scope is not None and not engine.is_naive():
        res = _bulk_record(scope, opdef, tensors, attrs, rng)
        if res is _FLUSH_AND_RUN:
            # non-recordable op (eager-only / unhashable attrs / sparse-
            # grad / tracer input): flush trigger (c), then run eagerly
            scope.flush("unrecordable")
            tensors = [engine.concretize(t) for t in tensors]
        elif res is not _RUN_EAGER:
            return res
    else:
        # no recorder on THIS thread, but an input may be the pending
        # output of another thread's open segment (or of a scope running
        # under NaiveEngine) — materialize before eager dispatch. Scan
        # first: the common no-bulk case must not pay a list rebuild
        for t in tensors:
            if type(t) is engine.PendingValue:
                tensors = [engine.concretize(v)
                           if type(v) is engine.PendingValue else v
                           for v in tensors]
                break
    tensors = _harmonize_devices(tensors)
    attr_items = tuple(sorted(attrs.items(), key=lambda kv: kv[0]))
    try:
        hash(attr_items)
        uncached = opdef.eager_only
    except TypeError:  # unhashable attr (e.g. list) — run uncached
        uncached = True
    if not uncached and attrs.get("_sparse_uid") is not None:
        # row-sparse-grad ops must inline into the SURROUNDING trace:
        # their custom-VJP side channel (parallel.sparse_grad) logs
        # backward tracers, which would escape a per-op jit's scope
        from ..parallel.sparse_grad import sparse_grad_active

        uncached = sparse_grad_active()
    # pin the execution platform from the concrete operands so in-trace
    # kernel dispatch (Pallas flash) targets where the op actually runs
    sample = tensors[0] if tensors else None
    platform = current_execution_platform(sample)
    with execution_platform(platform):
        if uncached:
            if _telemetry_state.enabled:
                telemetry.record_xla_dispatch("eager_uncached")
            if rng is not None:
                return opdef.fn(rng, *tensors, **attrs)
            if opdef.needs_rng:
                return opdef.fn(None, *tensors, **attrs)
            return opdef.fn(*tensors, **attrs)
        routing = _routing_knobs()
        fn, hit = _eager_executable(opdef.name, attr_items, len(tensors),
                                    rng is not None, platform, routing)
        if _telemetry_state.enabled:
            telemetry.record_xla_dispatch("eager_op")
        if not hit and _cmanifest.recorder() is not None:
            _cmanifest.record_signature("eager_op", {
                "op": opdef.name, "attrs": attr_items,
                "avals": tuple((tuple(t.shape), str(t.dtype))
                               if hasattr(t, "shape") else None
                               for t in tensors),
                "has_rng": rng is not None, "platform": platform,
                "routing": routing})
        if rng is not None:
            return fn(rng, *tensors)
        return fn(*tensors)


# ---------------------------------------------------------------------------
# Bulked execution: record-vs-execute fork + fused-segment cache.
#
# Reference analogue: CachedOp — MXNet wins its imperative perf back by
# bulking op sequences into single engine pushes keyed by a graph signature.
# Here an ``engine.bulk`` scope records ops into an ``engine.Segment``; the
# segment lowers to ONE jitted function compiled through ``_FUSED_CACHE``,
# keyed by the full (op, attrs, input shape/dtype, wiring, live-output)
# sequence, so a repeated loop body replays a compiled executable with zero
# retracing. See engine.py for the scope/flush machinery.
# ---------------------------------------------------------------------------

_RUN_EAGER = object()       # don't record; no flush needed (independent op)
_FLUSH_AND_RUN = object()   # non-recordable: flush segment, then run eagerly

_jax_cached = None


def _jax_mod():
    """Cached jax module for the per-recorded-op path (this module keeps
    jax imports lazy, but a sys.modules lookup per recorded op is the same
    per-call overhead class the engine hot-path hoists removed)."""
    global _jax_cached
    if _jax_cached is None:
        import jax

        _jax_cached = jax
    return _jax_cached


def _bulk_record(scope, opdef: OpDef, tensors, attrs, rng):
    """Try to append this op to the thread's open bulk segment.

    Returns the op's result (PendingValue(s)) when recorded, or one of the
    ``_RUN_EAGER`` / ``_FLUSH_AND_RUN`` sentinels when the op must execute
    eagerly.
    """
    _jax = _jax_mod()

    if opdef.eager_only:
        return _FLUSH_AND_RUN
    attr_items = tuple(sorted(attrs.items(), key=lambda kv: kv[0]))
    try:
        hash(attr_items)
    except TypeError:  # unhashable attr (e.g. nested list) — not keyable
        return _FLUSH_AND_RUN
    if attrs.get("_sparse_uid") is not None:
        # row-sparse-grad side channel logs backward tracers that must not
        # cross a fused-segment jit boundary (same rule as the per-op cache)
        from ..parallel.sparse_grad import sparse_grad_active

        if sparse_grad_active():
            return _FLUSH_AND_RUN

    # classify inputs; rng (a concrete PRNG key) is a leading runtime arg
    # but NOT an array input for the creation-op test below — a zero-tensor
    # random sampler is a creation op and must take the _RUN_EAGER path
    raw_inputs = list(tensors)
    n_prefix = 0
    if rng is not None:
        raw_inputs.insert(0, rng)
        n_prefix = 1
    elif opdef.needs_rng:  # gated-off rng: fn still expects the slot
        raw_inputs.insert(0, None)
        n_prefix = 1
    staged = []        # ("r", pv) | ("a", value) | ("s", literal)
    aval_key = []      # hashable per-input descriptors for shape inference
    seg = scope.segment
    has_array_input = False
    for i, t in enumerate(raw_inputs):
        if type(t) is engine.PendingValue:
            c = t._concrete
            if c is not None:
                t = c  # already flushed: plain runtime arg
            elif seg is not None and t.segment is seg:
                has_array_input = True
                staged.append(("r", t))
                aval_key.append(("v", t.aval.shape, t.aval.dtype))
                continue
            else:
                # pending output of ANOTHER segment (cross-thread handoff
                # or pre-nesting leftovers): materialize it
                t = t.force()
        if isinstance(t, _jax.core.Tracer):
            # already inside someone else's trace — recording would leak
            # the tracer into the fused jit's scope
            return _FLUSH_AND_RUN
        if t is None or isinstance(t, (bool, int, float, complex, str)):
            staged.append(("s", t))
            aval_key.append(("s", t))
            continue
        if not hasattr(t, "shape"):
            return _FLUSH_AND_RUN
        sh = getattr(t, "sharding", None)
        if sh is not None and getattr(sh, "num_devices", 1) > 1:
            # multi-device operands keep the eager path (its device
            # harmonization logic); bulking targets single-device chains
            return _FLUSH_AND_RUN
        if i >= n_prefix:
            has_array_input = True
        staged.append(("a", t))
        aval_key.append(("v", tuple(t.shape), t.dtype))
    if not has_array_input:
        # creation-style op (zeros/arange/...): no dataflow into the
        # segment, so nothing to defer — run eagerly WITHOUT flushing
        return _RUN_EAGER

    if seg is not None and not seg.flushed:
        platform = seg.platform
    else:
        from ..base import current_execution_platform

        sample = next((t for k, t in staged
                       if k == "a" and hasattr(t, "devices")), None)
        platform = current_execution_platform(sample)

    try:
        out_avals, out_is_seq = _segment_avals(
            opdef.name, attr_items, tuple(aval_key), platform)
    except Exception:
        # abstract eval failed (value-dependent op, bad shapes, ...): the
        # eager path reproduces the exact per-op error at the right line
        return _FLUSH_AND_RUN

    seg = scope.open_segment(platform)
    with seg._lock:
        if seg.flushed:  # another thread forced a flush mid-record
            seg = scope.open_segment(platform)
        node_index = len(seg.nodes)
        input_specs = []
        sig_inputs = []
        for kind, v in staged:
            if kind == "r" and (v.segment is not seg
                                or v._concrete is not None):
                # the segment was flushed (and reopened) between staging
                # and commit — the dependency is concrete now
                kind, v = "a", (v._concrete if v._concrete is not None
                                else v.force())
            if kind == "r":
                spec = ("r", v.node_index, v.out_index)
                input_specs.append(spec)
                sig_inputs.append(spec)
            elif kind == "a":
                idx = seg.add_const(v)
                input_specs.append(("a", idx))
                sig_inputs.append(("a", idx, tuple(v.shape), str(v.dtype)))
            else:
                input_specs.append(("s", v))
                sig_inputs.append(("s", v))
        sig = (opdef.name, attr_items, tuple(sig_inputs))
        node = engine._SegmentNode(
            opdef.name, opdef.fn, attr_items, tuple(input_specs),
            len(out_avals), out_is_seq, sig)
        seg.nodes.append(node)
        pvs = [engine.PendingValue(seg, node_index, oi,
                                   _jax.ShapeDtypeStruct(shape, dtype))
               for oi, (shape, dtype) in enumerate(out_avals)]
        seg.out_refs.append([engine.weakref.ref(pv) for pv in pvs])
        full = len(seg.nodes) >= scope.max_size
    if full:
        seg.flush("size")  # trigger (b): segment reached bulk(size)
    if out_is_seq:
        return tuple(pvs)
    return pvs[0]


@functools.lru_cache(maxsize=8192)
def _segment_avals(opname: str, attr_items: tuple, aval_key: tuple,
                   platform: str):
    """Output (shape, dtype) sequence of one op via ``jax.eval_shape`` —
    cached so steady-state recording never re-traces. ``aval_key`` entries:
    ``("v", shape, dtype)`` for runtime args, ``("s", literal)`` for
    static scalars/None."""
    import jax

    from ..base import execution_platform

    opdef = _REGISTRY[opname]
    attrs = dict(attr_items)
    avals = [jax.ShapeDtypeStruct(k[1], k[2]) for k in aval_key
             if k[0] == "v"]

    def pure(*arrs):
        it = iter(arrs)
        args = [next(it) if k[0] == "v" else k[1] for k in aval_key]
        return opdef.fn(*args, **attrs)

    with execution_platform(platform):
        out = jax.eval_shape(pure, *avals)
    out_is_seq = isinstance(out, (tuple, list))
    outs = tuple(out) if out_is_seq else (out,)
    return tuple((tuple(o.shape), o.dtype) for o in outs), out_is_seq


# signature -> jitted fused function; LRU-bounded through the service's
# fused_segment site cache. The signature encodes the complete segment
# semantics (per-node op/attrs/static-literals/wiring, runtime-arg
# shapes+dtypes, live-output mask, platform), so a hit replays a compiled
# executable for a structurally identical segment. Evictions are counted
# (mxnet_jit_cache_evictions_total{cache="fused_segment"}) and the evicted
# signature logged at debug — cache thrash used to be silent here.
_FUSED_CACHE_MAX = 1024
_FUSED_CACHE = None


def _fused_cache():
    global _FUSED_CACHE
    if _FUSED_CACHE is None:
        from ..compiler import service as _csvc

        _FUSED_CACHE = _csvc.shared_cache("fused_segment",
                                          maxsize=_FUSED_CACHE_MAX)
    return _FUSED_CACHE


def fused_segment_cache_clear() -> None:
    _fused_cache().clear()


def _build_fused(nodes, live_mask):
    """Lower a recorded segment into one pure function and jit it. The
    closure captures node structure only — everything it captures is part
    of the cache signature, so reuse across segments is sound."""
    import jax

    from ..base import MXNetError

    def fused_segment(*consts):
        env = {}
        for ni, node in enumerate(nodes):
            args = []
            for spec in node.input_specs:
                kind = spec[0]
                if kind == "r":
                    args.append(env[(spec[1], spec[2])])
                elif kind == "a":
                    args.append(consts[spec[1]])
                else:
                    args.append(spec[1])
            try:
                out = node.fn(*args, **dict(node.attr_items))
            except Exception as e:
                # flush-time errors must name the originating op — the
                # user's call site is long gone by now
                raise MXNetError(
                    f"error while executing bulked segment at op #{ni} "
                    f"({node.name!r}): {e}") from e
            outs = out if isinstance(out, (tuple, list)) else (out,)
            for oi, o in enumerate(outs):
                env[(ni, oi)] = o
        return tuple(env[k] for k in live_mask)

    fused_segment.__name__ = "fused_segment"
    return jax.jit(fused_segment)


def execute_segment(seg, reason: str) -> None:
    """Flush one segment: one fused XLA dispatch through the signature-
    keyed cache; resolve live PendingValues. Called (exactly once per
    segment) by ``engine.Segment.flush`` with the segment lock held."""
    from ..base import execution_platform

    t0 = time.perf_counter()
    live = []
    for refs in seg.out_refs:
        for ref in refs:
            pv = ref()
            if pv is not None:
                live.append(pv)
    live_mask = tuple((pv.node_index, pv.out_index) for pv in live)
    node_sigs = tuple(n.sig for n in seg.nodes)
    routing = _routing_knobs()
    cache = _fused_cache()
    key = _ckeys.signature("fused_segment", node_sigs,
                           platform=seg.platform, routing=routing,
                           extra=(live_mask,))
    jitted = cache.lookup(key)
    hit = jitted is not cache.MISS
    if not hit:
        jitted = _build_fused(tuple(seg.nodes), live_mask)
        cache.insert(key, jitted)
        if _cmanifest.recorder() is not None:
            _cmanifest.record_signature("fused_segment", {
                "nodes": node_sigs, "live": live_mask,
                "platform": seg.platform, "routing": routing})
    with execution_platform(seg.platform):
        outs = jitted(*seg.consts)
    if _telemetry_state.enabled:
        telemetry.record_xla_dispatch("fused_segment")
        telemetry.record_bulk_flush(reason, len(seg.nodes),
                                    time.perf_counter() - t0)
    for pv, val in zip(live, outs):
        pv._concrete = val
        engine.track(val)
    from .. import profiler

    if profiler.state() == "run":
        profiler.record_span("Bulk::flush", time.perf_counter() - t0)


# ---------------------------------------------------------------------------
# Manifest warm-start replay (compiler.warm_start's op-level sites).
# ---------------------------------------------------------------------------


def _platform_available(platform) -> bool:
    import jax

    if not platform:
        return False
    try:
        return bool(jax.devices(platform))
    except Exception:
        return False


# (op key, avals) fingerprints already driven by warm_eager_spec: a
# reload (or replica N) replaying the same manifest must not re-dispatch
# every recorded op on device — one zero-filled drive per signature per
# process is the whole point
_WARMED_EAGER: set = set()
_warmed_eager_lock = threading.Lock()


def warm_eager_spec(spec: dict) -> str:
    """Replay one ``eager_op`` manifest entry: rebuild the per-op jitted
    executable and drive one zero-filled dispatch at the recorded avals so
    jax's executable cache (and the persistent disk tier) is hot before
    real traffic. Returns the warm outcome ("replayed"/"deduped"/
    "skipped")."""
    import jax.numpy as jnp

    from .. import random_state
    from ..base import execution_platform
    from ..compiler import keys as _keys

    opname = spec.get("op")
    platform = spec.get("platform")
    if opname not in _REGISTRY or not _platform_available(platform):
        return "skipped"
    attr_items = tuple(spec.get("attrs", ()))
    avals = spec.get("avals", ())
    has_rng = bool(spec.get("has_rng"))
    warmed_fp = _keys.fingerprint(_keys.encode(
        (opname, attr_items, avals, has_rng, platform,
         _routing_knobs())))
    with _warmed_eager_lock:
        if warmed_fp in _WARMED_EAGER:
            return "deduped"
    fn, hit = _eager_executable(opname, attr_items, len(avals), has_rng,
                                platform, _routing_knobs(), record=False)
    args = []
    for av in avals:
        if av is None:
            args.append(None)
        else:
            shape, dtype = av
            args.append(jnp.zeros(tuple(shape), dtype=dtype))
    with random_state.preserved_stream():
        rng = random_state.get_state_key() if has_rng else None
        with execution_platform(platform):
            out = fn(rng, *args) if has_rng else fn(*args)
    import jax

    jax.block_until_ready(out)
    # marked warm only AFTER the dispatch succeeds: a failed replay must
    # stay retryable on the next warm_start, not report "deduped" forever
    with _warmed_eager_lock:
        _WARMED_EAGER.add(warmed_fp)
    return "deduped" if hit else "replayed"


def warm_fused_spec(spec: dict) -> str:
    """Replay one ``fused_segment`` manifest entry: rebuild the segment
    program from the registry, AOT-compile it through the service's
    executable table (``jit(...).lower().compile()``) and seat it in the
    fused cache under the exact signature live recording computes — a
    later structurally identical segment flushes straight into the warm
    executable."""
    import jax

    from ..base import execution_platform
    from ..compiler import service as _csvc

    node_sigs = spec.get("nodes")
    live_mask = spec.get("live")
    platform = spec.get("platform")
    if not node_sigs or live_mask is None \
            or not _platform_available(platform):
        return "skipped"
    node_sigs = tuple(node_sigs)
    live_mask = tuple(live_mask)
    cache = _fused_cache()
    key = _ckeys.signature("fused_segment", node_sigs, platform=platform,
                           routing=_routing_knobs(), extra=(live_mask,))
    if key in cache:
        return "deduped"
    nodes = []
    const_avals = {}
    for nsig in node_sigs:
        opname, attr_items, sig_inputs = nsig
        opdef = _REGISTRY.get(opname)
        if opdef is None:
            return "skipped"
        input_specs = []
        for s in sig_inputs:
            if s[0] == "a":
                input_specs.append(("a", s[1]))
                const_avals[s[1]] = (tuple(s[2]), s[3])
            else:
                input_specs.append(tuple(s))
        nodes.append(engine._SegmentNode(
            opname, opdef.fn, tuple(attr_items), tuple(input_specs),
            0, False, nsig))
    nodes = tuple(nodes)
    if sorted(const_avals) != list(range(len(const_avals))):
        return "skipped"    # torn spec: const slots must be dense
    sds = [jax.ShapeDtypeStruct(const_avals[i][0], const_avals[i][1])
           for i in range(len(const_avals))]
    with execution_platform(platform):
        lowered = _build_fused(nodes, live_mask).lower(*sds)
        fp = _csvc.fingerprint_lowered(lowered)
        compiled = _csvc.exec_table.get_or_build(fp, lowered.compile)
    guarded = _csvc.GuardedExec(
        compiled, lambda: _build_fused(nodes, live_mask))
    cache.insert(key, guarded)
    return "replayed"
