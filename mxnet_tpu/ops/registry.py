"""Operator registry.

Reference: the nnvm op registry (``3rdparty/tvm/nnvm/include/nnvm/op.h``)
plus MXNet's per-op registration pattern
(``src/operator/... :: NNVM_REGISTER_OP(x).set_attr<FCompute>(...)``).

In the TPU-native build an operator is a **pure JAX function**
``fn(*tensors, **attrs) -> array | tuple`` registered by its MXNet name.
The same registry serves:

* the imperative frontend (``mx.nd.*`` wrappers dispatch here, with an
  eager per-op executable cache — the equivalent of MXNet pushing one op
  to the ThreadedEngine, see §7.3.2 of SURVEY.md);
* the symbolic frontend (``mx.sym.*`` records the op name + attrs into a
  graph; the Executor looks implementations up here at jit time);
* autograd (``jax.vjp`` over the pure function replaces per-op FGradient
  attrs — XLA derives the backward, no hand-written grads needed except
  where MXNet defines *non-mathematical* gradients, e.g. SoftmaxOutput,
  which use ``jax.custom_vjp`` in their impl).

Attr convention: tensor inputs are positional parameters; attributes are
keyword(-only) parameters with defaults. The wrapper generators use
``inspect`` to split the two.
"""
from __future__ import annotations

import functools
import inspect
import threading
import time
from typing import Callable, Dict, List, NamedTuple, Optional, Sequence

from .. import telemetry
from ..telemetry import _state as _telemetry_state

__all__ = ["OpDef", "AttrSpec", "attr", "register", "get_op", "list_ops",
           "alias", "validate_attrs"]


class AttrSpec(NamedTuple):
    """Typed operator-attribute declaration.

    The dmlc::Parameter equivalent (reference: ``include/dmlc/parameter.h``
    — typed param structs with range checks whose descriptions flow into
    the generated op docs). Declared per-op at ``register(attrs=[...])``;
    validated on every call; rendered into the ``mx.nd.*`` / ``mx.sym.*``
    wrapper docstrings.
    """

    name: str
    type: object = None          # python type or tuple of types
    doc: str = ""
    low: Optional[float] = None  # inclusive numeric bounds
    high: Optional[float] = None
    choices: Optional[tuple] = None

    def describe(self):
        parts = []
        if self.type is not None:
            ts = self.type if isinstance(self.type, tuple) else (self.type,)
            parts.append("/".join(t.__name__ for t in ts))
        if self.choices is not None:
            parts.append("one of " + ", ".join(map(repr, self.choices)))
        if self.low is not None or self.high is not None:
            lo = "-inf" if self.low is None else self.low
            hi = "inf" if self.high is None else self.high
            parts.append(f"range [{lo}, {hi}]")
        return ", ".join(parts)


def attr(name, type=None, doc="", low=None, high=None, choices=None):
    return AttrSpec(name, type, doc, low, high,
                    tuple(choices) if choices is not None else None)


_COERCIBLE = {
    int: (int,),
    float: (int, float),
    bool: (bool, int),
    str: (str,),
    tuple: (tuple, list, int),
}


def validate_attrs(opdef: "OpDef", attrs: Dict) -> None:
    """Raise a typed MXNetError naming the op, attribute and constraint
    for out-of-spec attribute values. Undeclared attributes pass (specs
    cover the documented surface, not every internal knob)."""
    specs = opdef.attr_specs
    if not specs:
        return
    from ..base import MXNetError

    import numpy as _np

    for k, v in attrs.items():
        spec = specs.get(k)
        if spec is None or v is None:
            continue
        if isinstance(v, (_np.generic,)):
            v = v.item()
        if spec.type is not None:
            want = spec.type if isinstance(spec.type, tuple) else (spec.type,)
            ok = any(isinstance(v, _COERCIBLE.get(t, (t,))) for t in want)
            # bools are ints in python — reject bool where int expected
            if ok and bool not in want and isinstance(v, bool):
                ok = False
            if not ok:
                raise MXNetError(
                    f"{opdef.name}: attribute {k}={v!r} has type "
                    f"{type(v).__name__}; expected {spec.describe()}")
        if spec.choices is not None and v not in spec.choices:
            raise MXNetError(
                f"{opdef.name}: attribute {k}={v!r} must be "
                f"{spec.describe()}")
        vals = v if isinstance(v, (tuple, list)) else (v,)
        for item in vals:
            if not isinstance(item, (int, float)) or isinstance(item, bool):
                continue
            if spec.low is not None and item < spec.low:
                raise MXNetError(
                    f"{opdef.name}: attribute {k}={v!r} below "
                    f"{spec.describe()}")
            if spec.high is not None and item > spec.high:
                raise MXNetError(
                    f"{opdef.name}: attribute {k}={v!r} above "
                    f"{spec.describe()}")


def render_attr_docs(opdef: "OpDef") -> str:
    """Numpy-style attribute section for generated wrapper docstrings."""
    if not opdef.attr_specs:
        return ""
    lines = ["", "", "Attributes", "----------"]
    for spec in opdef.attr_specs.values():
        head = spec.name
        desc = spec.describe()
        if desc:
            head += f" : {desc}"
        lines.append(head)
        if spec.doc:
            lines.append(f"    {spec.doc}")
    return "\n".join(lines)


class OpDef(NamedTuple):
    name: str
    fn: Callable
    # names of tensor (array) parameters, in order
    tensor_params: tuple
    # tensor params that may be None (optional inputs like bias)
    optional_tensor_params: frozenset
    # attr param names
    attr_params: tuple
    # whether the fn consumes a PRNG key as first argument (random ops)
    needs_rng: bool
    # number of outputs; None = infer from returned tuple
    num_outputs: Optional[int]
    # if True, the imperative wrapper resolves autograd.is_training() and
    # passes it as the `_training` attr
    pass_training_flag: bool
    # accepts variable number of tensor inputs as a leading list
    variadic: bool
    # op must run untraced (dynamic output shapes — e.g. boolean_mask)
    eager_only: bool
    # typed attribute declarations (AttrSpec by name); None = undeclared
    attr_specs: Optional[Dict] = None
    # fn has **kwargs: forward ALL attrs, not just declared attr_params
    # (the `Custom` op's user-defined attribute surface)
    var_attrs: bool = False
    # optional attrs -> bool predicate: draw/consume a PRNG key only when
    # it returns True (ops like sdp_attention that are random only when a
    # dropout attr is set — an unconditional draw would advance the
    # global stream on every eval-mode call, a reproducibility trap).
    # When gated off the fn still receives rng=None positionally.
    rng_gate: Optional[Callable] = None


_REGISTRY: Dict[str, OpDef] = {}


def register(
    name: Optional[str] = None,
    aliases: Sequence[str] = (),
    needs_rng: bool = False,
    num_outputs: Optional[int] = None,
    pass_training_flag: bool = False,
    variadic: bool = False,
    eager_only: bool = False,
    attrs: Sequence[AttrSpec] = (),
    rng_gate: Optional[Callable] = None,
):
    """Decorator registering a pure-JAX op implementation.

    ``attrs``: optional typed AttrSpec declarations (the dmlc::Parameter
    equivalent) — validated on every call, rendered into wrapper docs.
    """

    def deco(fn):
        opname = name or fn.__name__
        sig = inspect.signature(fn)
        tensor_params: List[str] = []
        optional: List[str] = []
        attr_params: List[str] = []
        for pname, p in sig.parameters.items():
            if needs_rng and pname == "rng":
                continue
            if pname == "_training":
                continue
            if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
                if p.kind == p.POSITIONAL_OR_KEYWORD and p.default is not inspect.Parameter.empty and not _is_tensor_default(p.default):
                    attr_params.append(pname)
                else:
                    tensor_params.append(pname)
                    if p.default is None:
                        optional.append(pname)
            elif p.kind == p.KEYWORD_ONLY:
                attr_params.append(pname)
            elif p.kind == p.VAR_POSITIONAL:
                # variadic tensor inputs (e.g. Concat, add_n)
                tensor_params.append(pname)
        opdef = OpDef(
            name=opname,
            fn=fn,
            tensor_params=tuple(tensor_params),
            optional_tensor_params=frozenset(optional),
            attr_params=tuple(attr_params),
            needs_rng=needs_rng,
            num_outputs=num_outputs,
            pass_training_flag=pass_training_flag,
            variadic=variadic or any(
                p.kind == p.VAR_POSITIONAL for p in sig.parameters.values()
            ),
            eager_only=eager_only,
            attr_specs={s.name: s for s in attrs} if attrs else None,
            var_attrs=any(p.kind == p.VAR_KEYWORD
                          for p in sig.parameters.values()),
            rng_gate=rng_gate,
        )
        _REGISTRY[opname] = opdef
        for a in aliases:
            _REGISTRY[a] = opdef
        fn.__opdef__ = opdef
        return fn

    return deco


def _is_tensor_default(default):
    # positional params whose default is None are optional tensors (bias=None)
    return default is None


def alias(new_name: str, existing: str) -> None:
    _REGISTRY[new_name] = _REGISTRY[existing]


def get_op(name: str) -> OpDef:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise NotImplementedError(
            f"operator {name!r} is not implemented in mxnet_tpu "
            f"(see SURVEY.md §2.1 op families for the porting roadmap)"
        ) from None


def has_op(name: str) -> bool:
    return name in _REGISTRY


def list_ops() -> List[str]:
    return sorted(_REGISTRY)


# ---------------------------------------------------------------------------
# Eager single-op executable cache.
#
# Reference analogue: MXNet's imperative path pays ~µs dispatch per op
# (SURVEY.md §3.1); ours pays a jit-cache lookup. Executables are cached by
# (op name, attr values); XLA itself caches by input shape/dtype underneath.
# ---------------------------------------------------------------------------


# hit/miss telemetry: the lru-cached body below only runs on a miss, and
# only in the calling thread, so a thread-local flag is race-free where a
# cache_info().misses delta would misattribute a concurrent thread's miss
_cache_probe = threading.local()


@functools.lru_cache(maxsize=4096)
def _cached_call(opname: str, attr_items: tuple, n_tensors: int,
                 has_rng: bool, platform: str):
    _cache_probe.miss = True
    # `platform` keys the cache even though the traced fn only reads it
    # ambiently: op impls dispatch on current_execution_platform() at
    # TRACE time (Pallas kernels, int8 MXU paths), so one executable per
    # platform — otherwise the first-traced platform's body would be
    # served everywhere (round-3 review finding, verified live)
    import jax

    opdef = _REGISTRY[opname]
    attrs = dict(attr_items)

    if has_rng:
        def pure(rng, *tensors):
            return opdef.fn(rng, *tensors, **attrs)
    elif opdef.needs_rng:
        # rng draw gated off (rng_gate): the fn still expects the slot
        def pure(*tensors):
            return opdef.fn(None, *tensors, **attrs)
    else:
        def pure(*tensors):
            return opdef.fn(*tensors, **attrs)

    pure.__name__ = opname
    return jax.jit(pure)


def _harmonize_devices(tensors):
    """Mixed single-device / mesh-sharded operands: replicate the
    single-device ones onto the sharded operand's mesh.

    This is what lets a model trained by parallel.TrainStep (params laid out
    over a Mesh) be used eagerly afterwards — ``net(x)`` with a host-side
    ``x`` — without the user re-placing anything. The reference's analogue
    is ``as_in_context`` coercion; here the "context" is the mesh layout.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    # In-trace operands are tracers on one logical device set already; and
    # Tracer.sharding raises an AttributeError whose MESSAGE construction
    # walks the whole jaxpr for provenance — profiled at ~70% of total
    # model trace time when this ran per-op (see PERF.md round 3).
    for t in tensors:
        if isinstance(t, jax.core.Tracer):
            return tensors

    mesh = None
    mixed = False
    for t in tensors:
        sh = getattr(t, "sharding", None)
        if isinstance(sh, NamedSharding) and sh.num_devices > 1:
            if mesh is None:
                mesh = sh.mesh
        elif hasattr(t, "sharding"):
            mixed = True
    if mesh is None or not mixed:
        return tensors
    rep = NamedSharding(mesh, PartitionSpec())
    out = []
    for t in tensors:
        sh = getattr(t, "sharding", None)
        if isinstance(sh, NamedSharding) and sh.num_devices > 1:
            out.append(t)
        else:
            out.append(jax.device_put(t, rep))
    return type(tensors)(out) if isinstance(tensors, tuple) else out


def eager_call(opdef: OpDef, tensors, attrs, rng=None):
    """Execute an op eagerly through the per-op executable cache.

    Telemetry (MXNET_TELEMETRY=1): per-op invocation count + host dispatch
    latency; disabled mode costs exactly this one branch.
    """
    if _telemetry_state.enabled:
        t0 = time.perf_counter()
        try:
            return _eager_call(opdef, tensors, attrs, rng)
        finally:
            telemetry.record_op_dispatch(
                opdef.name, time.perf_counter() - t0)
    return _eager_call(opdef, tensors, attrs, rng)


def _eager_call(opdef: OpDef, tensors, attrs, rng=None):
    from ..base import current_execution_platform, execution_platform

    if opdef.attr_specs:
        validate_attrs(opdef, attrs)
    tensors = _harmonize_devices(tensors)
    attr_items = tuple(sorted(attrs.items(), key=lambda kv: kv[0]))
    try:
        hash(attr_items)
        uncached = opdef.eager_only
    except TypeError:  # unhashable attr (e.g. list) — run uncached
        uncached = True
    if not uncached and attrs.get("_sparse_uid") is not None:
        # row-sparse-grad ops must inline into the SURROUNDING trace:
        # their custom-VJP side channel (parallel.sparse_grad) logs
        # backward tracers, which would escape a per-op jit's scope
        from ..parallel.sparse_grad import sparse_grad_active

        uncached = sparse_grad_active()
    # pin the execution platform from the concrete operands so in-trace
    # kernel dispatch (Pallas flash) targets where the op actually runs
    sample = tensors[0] if tensors else None
    platform = current_execution_platform(sample)
    with execution_platform(platform):
        if uncached:
            if rng is not None:
                return opdef.fn(rng, *tensors, **attrs)
            if opdef.needs_rng:
                return opdef.fn(None, *tensors, **attrs)
            return opdef.fn(*tensors, **attrs)
        if _telemetry_state.enabled:
            _cache_probe.miss = False
            fn = _cached_call(opdef.name, attr_items, len(tensors),
                              rng is not None, platform)
            telemetry.record_cache("eager_op", hit=not _cache_probe.miss)
        else:
            fn = _cached_call(opdef.name, attr_items, len(tensors),
                              rng is not None, platform)
        if rng is not None:
            return fn(rng, *tensors)
        return fn(*tensors)
