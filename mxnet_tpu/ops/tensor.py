"""Shape/indexing/reduction/linalg operators.

Reference: ``src/operator/tensor/matrix_op.cc`` (reshape/transpose/slice/
concat/stack/...), ``broadcast_reduce_op_value.cc`` (sum/mean/...),
``indexing_op.cc`` (take/one_hot/gather_nd/scatter_nd), ``ordering_op.cc``
(topk/sort/argsort), ``init_op.cc`` (zeros/ones/arange), ``dot.cc``,
``la_op.cc`` (linalg).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register, alias

# ---------------------------------------------------------------------------
# shape manipulation
# ---------------------------------------------------------------------------


def _reshape_with_magic(shape_in, target):
    """MXNet Reshape supports magic values 0 (copy dim), -1 (infer),
    -2 (copy rest), -3 (merge two dims), -4 (split dim).
    Reference: src/operator/tensor/matrix_op.cc :: ReshapeShape."""
    target = list(target)
    out = []
    src = list(shape_in)
    i = 0  # index into src
    j = 0  # index into target
    while j < len(target):
        t = target[j]
        if t == 0:
            out.append(src[i]); i += 1
        elif t == -1:
            out.append(-1); i += 1
        elif t == -2:
            out.extend(src[i:]); i = len(src)
        elif t == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif t == -4:
            d1, d2 = target[j + 1], target[j + 2]
            if d1 == -1:
                d1 = src[i] // d2
            if d2 == -1:
                d2 = src[i] // d1
            out.extend([d1, d2]); i += 1; j += 2
        else:
            out.append(t); i += 1
        j += 1
    # resolve a single -1
    if out.count(-1) == 1:
        known = 1
        for d in out:
            if d != -1:
                known *= d
        total = 1
        for d in shape_in:
            total *= d
        out[out.index(-1)] = total // max(known, 1)
    return tuple(out)


@register("Reshape", aliases=["reshape"])
def reshape_op(data, *, shape=(), reverse=False):
    tgt = _reshape_with_magic(data.shape[::-1] if reverse else data.shape,
                              tuple(shape)[::-1] if reverse else tuple(shape))
    if reverse:
        tgt = tgt[::-1]
    return jnp.reshape(data, tgt)


@register("reshape_like")
def reshape_like(lhs, rhs):
    return jnp.reshape(lhs, rhs.shape)


@register("Flatten", aliases=["flatten"])
def flatten_op(data):
    return jnp.reshape(data, (data.shape[0], -1))


@register("transpose")
def transpose(data, *, axes=()):
    axes = tuple(axes) if axes else None
    return jnp.transpose(data, axes)


@register("expand_dims")
def expand_dims(data, *, axis=0):
    return jnp.expand_dims(data, axis)


@register("squeeze")
def squeeze(data, *, axis=None):
    if axis is None:
        return jnp.squeeze(data)
    return jnp.squeeze(data, axis if isinstance(axis, int) else tuple(axis))


@register("broadcast_to")
def broadcast_to(data, *, shape=()):
    tgt = tuple(s if t == 0 else t for s, t in zip(data.shape, shape))
    return jnp.broadcast_to(data, tgt)


@register("broadcast_like")
def broadcast_like(lhs, rhs, *, lhs_axes=None, rhs_axes=None):
    if lhs_axes is None:
        return jnp.broadcast_to(lhs, rhs.shape)
    tgt = list(lhs.shape)
    for la, ra in zip(lhs_axes, rhs_axes):
        tgt[la] = rhs.shape[ra]
    return jnp.broadcast_to(lhs, tuple(tgt))


@register("broadcast_axis", aliases=["broadcast_axes"])
def broadcast_axis(data, *, axis=(), size=()):
    if isinstance(axis, int):
        axis, size = (axis,), (size,)
    tgt = list(data.shape)
    for a, s in zip(axis, size):
        tgt[a] = s
    return jnp.broadcast_to(data, tuple(tgt))


@register("slice")
def slice_op(data, *, begin=(), end=(), step=()):
    slices = []
    step = step or (None,) * len(begin)
    for i, (b, e) in enumerate(zip(begin, end)):
        s = step[i] if i < len(step) else None
        slices.append(slice(b, e, s))
    return data[tuple(slices)]


@register("slice_axis")
def slice_axis(data, *, axis=0, begin=0, end=None):
    idx = [slice(None)] * data.ndim
    idx[axis] = slice(begin, end)
    return data[tuple(idx)]


@register("slice_like")
def slice_like(data, shape_like, *, axes=()):
    axes = tuple(axes) if axes else tuple(range(shape_like.ndim))
    idx = [slice(None)] * data.ndim
    for a in axes:
        idx[a] = slice(0, shape_like.shape[a])
    return data[tuple(idx)]


@register("Concat", aliases=["concat"], variadic=True)
def concat(*data, dim=1, num_args=None):
    return jnp.concatenate(data, axis=dim)


@register("stack", variadic=True)
def stack(*data, axis=0, num_args=None):
    return jnp.stack(data, axis=axis)


@register("split", aliases=["SliceChannel"])
def split(data, *, num_outputs=1, axis=1, squeeze_axis=False):
    parts = jnp.split(data, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


@register("split_v2")
def split_v2(data, *, indices=(), axis=0, squeeze_axis=False, sections=0):
    if sections > 0:
        parts = jnp.split(data, sections, axis=axis)
    else:
        parts = jnp.split(data, list(indices), axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


@register("tile")
def tile(data, *, reps=()):
    return jnp.tile(data, tuple(reps))


@register("repeat")
def repeat(data, *, repeats=1, axis=None):
    return jnp.repeat(data, repeats, axis=axis)


@register("Pad", aliases=["pad"])
def pad_op(data, *, mode="constant", pad_width=(), constant_value=0.0):
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(len(pad_width) // 2)]
    jmode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode]
    if jmode == "constant":
        return jnp.pad(data, pw, mode="constant", constant_values=constant_value)
    return jnp.pad(data, pw, mode=jmode)


@register("flip", aliases=["reverse"])
def flip(data, *, axis=()):
    ax = (axis,) if isinstance(axis, int) else tuple(axis)
    return jnp.flip(data, ax)


@register("swapaxes", aliases=["SwapAxis"])
def swapaxes(data, *, dim1=0, dim2=0):
    return jnp.swapaxes(data, dim1, dim2)


@register("depth_to_space")
def depth_to_space(data, *, block_size=1):
    n, c, h, w = data.shape
    b = block_size
    x = data.reshape(n, b, b, c // (b * b), h, w)
    x = x.transpose(0, 3, 4, 1, 5, 2)
    return x.reshape(n, c // (b * b), h * b, w * b)


@register("space_to_depth")
def space_to_depth(data, *, block_size=1):
    n, c, h, w = data.shape
    b = block_size
    x = data.reshape(n, c, h // b, b, w // b, b)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * b * b, h // b, w // b)


# ---------------------------------------------------------------------------
# indexing
# ---------------------------------------------------------------------------


@register("take")
def take(a, indices, *, axis=0, mode="clip"):
    jmode = {"clip": "clip", "wrap": "wrap", "raise": "clip"}[mode]
    return jnp.take(a, indices.astype(jnp.int32), axis=axis, mode=jmode)


@register("batch_take")
def batch_take(a, indices):
    flat = a.reshape(-1)
    offs = jnp.arange(a.shape[0]) * a.shape[1]
    return flat[indices.astype(jnp.int32) + offs.astype(jnp.int32)]


@register("pick")
def pick(data, index, *, axis=-1, keepdims=False, mode="clip"):
    # reference: mode='clip' clamps out-of-range indices, 'wrap' takes
    # them modulo the axis length
    if mode == "wrap":
        idx = index.astype(jnp.int32) % data.shape[axis]
    else:
        idx = jnp.clip(index.astype(jnp.int32), 0, data.shape[axis] - 1)
    picked = jnp.take_along_axis(data, jnp.expand_dims(idx, axis), axis=axis)
    if not keepdims:
        picked = jnp.squeeze(picked, axis=axis)
    return picked


@register("one_hot")
def one_hot(indices, *, depth=1, on_value=1.0, off_value=0.0, dtype="float32"):
    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth, dtype=jnp.dtype(dtype))
    return oh * on_value + (1.0 - oh) * off_value


@register("gather_nd")
def gather_nd(data, indices):
    idx = tuple(indices.astype(jnp.int32)[i] for i in range(indices.shape[0]))
    return data[idx]


@register("scatter_nd")
def scatter_nd(data, indices, *, shape=()):
    out = jnp.zeros(tuple(shape), dtype=data.dtype)
    idx = tuple(indices.astype(jnp.int32)[i] for i in range(indices.shape[0]))
    return out.at[idx].add(data)


@register("where_nd", aliases=["_np_where"])
def where_nd(condition, x, y):
    return jnp.where(condition != 0, x, y)


@register("boolean_mask", aliases=["_contrib_boolean_mask"], eager_only=True)
def boolean_mask(data, index, *, axis=0):
    # Dynamic-shape op: TPU-hostile under jit; registered eager_only so the
    # imperative path runs it untraced (host-side shape computation).
    mask = np.asarray(index) != 0
    return jnp.compress(mask, data, axis=axis)


@register("SequenceMask", aliases=["sequence_mask"])
def sequence_mask(data, sequence_length=None, *, use_sequence_length=False, value=0.0, axis=0):
    if not use_sequence_length or sequence_length is None:
        return data
    # data: (seq, batch, ...) for axis=0 or (batch, seq, ...) for axis=1
    seq_len = data.shape[axis]
    pos = jnp.arange(seq_len)
    if axis == 0:
        mask = pos[:, None] < sequence_length[None, :].astype(jnp.int32)
    else:
        mask = pos[None, :] < sequence_length[:, None].astype(jnp.int32)
    mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, jnp.asarray(value, dtype=data.dtype))


@register("SequenceLast")
def sequence_last(data, sequence_length=None, *, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        idx = data.shape[axis] - 1
        return jnp.take(data, idx, axis=axis)
    last = (sequence_length.astype(jnp.int32) - 1)
    if axis == 0:
        return jnp.take_along_axis(
            data, last.reshape((1, -1) + (1,) * (data.ndim - 2)), axis=0
        ).squeeze(0)
    return jnp.take_along_axis(
        data, last.reshape((-1, 1) + (1,) * (data.ndim - 2)), axis=1
    ).squeeze(1)


@register("SequenceReverse")
def sequence_reverse(data, sequence_length=None, *, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=0)
    seq_len = data.shape[0]
    pos = jnp.arange(seq_len)[:, None]
    sl = sequence_length.astype(jnp.int32)[None, :]
    src = jnp.where(pos < sl, sl - 1 - pos, pos)  # (seq, batch)
    src = src.reshape(src.shape + (1,) * (data.ndim - 2))
    return jnp.take_along_axis(data, jnp.broadcast_to(src, data.shape), axis=0)


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------


def _norm_axis(axis):
    if axis is None or axis == ():
        return None
    if isinstance(axis, int):
        return (axis,)
    return tuple(axis)


def _reduce(name, fn, aliases=()):
    def impl(data, *, axis=None, keepdims=False, exclude=False):
        ax = _norm_axis(axis)
        if exclude and ax is not None:
            ax = tuple(i for i in range(data.ndim) if i not in ax)
        return fn(data, axis=ax, keepdims=keepdims)

    impl.__name__ = name
    register(name, aliases=list(aliases))(impl)


_reduce("sum", jnp.sum, aliases=["sum_axis"])
_reduce("mean", jnp.mean)
_reduce("prod", jnp.prod)
_reduce("max", jnp.max, aliases=["max_axis"])
_reduce("min", jnp.min, aliases=["min_axis"])
_reduce("nansum", jnp.nansum)
_reduce("nanprod", jnp.nanprod)


@register("norm")
def norm(data, *, ord=2, axis=None, keepdims=False):
    ax = _norm_axis(axis)
    if ord == 1:
        return jnp.sum(jnp.abs(data), axis=ax, keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(data), axis=ax, keepdims=keepdims))


@register("L2Normalization")
def l2_normalization(data, *, eps=1e-10, mode="instance"):
    if mode == "instance":
        ax = tuple(range(1, data.ndim))
    elif mode == "channel":
        ax = (1,)
    else:  # spatial
        ax = tuple(range(2, data.ndim))
    denom = jnp.sqrt(jnp.sum(jnp.square(data), axis=ax, keepdims=True) + eps)
    return data / denom


@register("argmax")
def argmax(data, *, axis=None, keepdims=False):
    out = jnp.argmax(data, axis=axis)
    if keepdims and axis is not None:
        out = jnp.expand_dims(out, axis)
    return out.astype(jnp.float32)


@register("argmin")
def argmin(data, *, axis=None, keepdims=False):
    out = jnp.argmin(data, axis=axis)
    if keepdims and axis is not None:
        out = jnp.expand_dims(out, axis)
    return out.astype(jnp.float32)


@register("argmax_channel")
def argmax_channel(data):
    return jnp.argmax(data, axis=1).astype(jnp.float32)


# ---------------------------------------------------------------------------
# ordering (reference: src/operator/tensor/ordering_op.cc)
# ---------------------------------------------------------------------------


@register("topk")
def topk(data, *, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    d = -data if is_ascend else data
    sel_vals, raw_idx = jax.lax.top_k(jnp.moveaxis(d, axis, -1), k)
    vals = -sel_vals if is_ascend else sel_vals
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(raw_idx, -1, axis).astype(jnp.dtype(dtype))
    if ret_typ == "indices":
        return idx
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return vals, idx
    if ret_typ == "mask":
        # 1 at each top-k position along axis, 0 elsewhere (reference
        # ordering_op.cc ret_typ=mask). Built from the RAW integer
        # indices — the dtype-cast idx (default float32) corrupts indices
        # past 2^24.
        n = data.shape[axis]
        mask = jax.nn.one_hot(raw_idx, n, dtype=data.dtype).sum(axis=-2)
        return jnp.moveaxis(mask, -1, axis)
    raise ValueError(ret_typ)


@register("sort")
def sort(data, *, axis=-1, is_ascend=True):
    s = jnp.sort(data, axis=axis)
    return s if is_ascend else jnp.flip(s, axis=axis)


@register("argsort")
def argsort(data, *, axis=-1, is_ascend=True, dtype="float32"):
    s = jnp.argsort(data, axis=axis)
    if not is_ascend:
        s = jnp.flip(s, axis=axis)
    return s.astype(jnp.dtype(dtype))


@register("shuffle", aliases=["_shuffle"], needs_rng=True)
def shuffle(rng, data):
    return jax.random.permutation(rng, data, axis=0)


# ---------------------------------------------------------------------------
# linalg (reference: src/operator/tensor/dot.cc, la_op.cc)
# ---------------------------------------------------------------------------


@register("dot")
def dot(lhs, rhs, *, transpose_a=False, transpose_b=False):
    a = lhs.T if transpose_a else lhs
    b = rhs.T if transpose_b else rhs
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b)
    # MXNet dot: contract last axis of a with first axis of b
    return jnp.tensordot(a, b, axes=([a.ndim - 1], [0]))


@register("batch_dot")
def batch_dot(lhs, rhs, *, transpose_a=False, transpose_b=False):
    a = jnp.swapaxes(lhs, -1, -2) if transpose_a else lhs
    b = jnp.swapaxes(rhs, -1, -2) if transpose_b else rhs
    return jnp.matmul(a, b)


@register("khatri_rao", variadic=True)
def khatri_rao(*args):
    out = args[0]
    for m in args[1:]:
        out = jnp.einsum("i...,j...->ij...", out, m).reshape(-1, out.shape[-1])
    return out


@register("_linalg_gemm2", aliases=["linalg_gemm2"])
def linalg_gemm2(A, B, *, transpose_a=False, transpose_b=False, alpha=1.0, axis=-2):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b)


@register("_linalg_gemm", aliases=["linalg_gemm"])
def linalg_gemm(A, B, C, *, transpose_a=False, transpose_b=False, alpha=1.0, beta=1.0, axis=-2):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b) + beta * C


@register("_linalg_potrf", aliases=["linalg_potrf"])
def linalg_potrf(A):
    return jnp.linalg.cholesky(A)


@register("_linalg_trsm", aliases=["linalg_trsm"])
def linalg_trsm(A, B, *, transpose=False, rightside=False, lower=True, alpha=1.0):
    a = jnp.swapaxes(A, -1, -2) if transpose else A
    if rightside:
        out = jnp.swapaxes(
            jax.scipy.linalg.solve_triangular(
                jnp.swapaxes(a, -1, -2), jnp.swapaxes(alpha * B, -1, -2),
                lower=not lower if transpose else lower,
            ), -1, -2)
        return out
    return jax.scipy.linalg.solve_triangular(a, alpha * B, lower=lower != transpose)


@register("_linalg_syrk", aliases=["linalg_syrk"])
def linalg_syrk(A, *, transpose=False, alpha=1.0):
    a = jnp.swapaxes(A, -1, -2) if transpose else A
    return alpha * jnp.matmul(a, jnp.swapaxes(a, -1, -2))


@register("_linalg_gelqf", aliases=["linalg_gelqf"], num_outputs=2)
def linalg_gelqf(A):
    """LQ factorization A = L·Q with Q row-orthonormal (reference:
    la_op.cc::gelqf — LAPACK *gelqf/*orglq). Computed as the transpose of
    jnp's QR: A^T = Q'R'  =>  A = R'^T Q'^T = L Q."""
    q, r = jnp.linalg.qr(jnp.swapaxes(A, -1, -2), mode="reduced")
    return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)


@register("_linalg_syevd", aliases=["linalg_syevd"], num_outputs=2)
def linalg_syevd(A):
    """Symmetric eigendecomposition A = U^T·diag(L)·U (reference:
    la_op.cc::syevd — rows of the returned U are the eigenvectors, so
    U @ A @ U^T = diag(L))."""
    w, v = jnp.linalg.eigh(A)
    return jnp.swapaxes(v, -1, -2), w


@register("_linalg_potri", aliases=["linalg_potri"])
def linalg_potri(A):
    """Inverse from a Cholesky factor: (A·A^T)^-1 given lower-triangular A
    (reference: la_op.cc::potri)."""
    eye = jnp.broadcast_to(jnp.eye(A.shape[-1], dtype=A.dtype), A.shape)
    inv_a = jax.scipy.linalg.solve_triangular(A, eye, lower=True)
    return jnp.matmul(jnp.swapaxes(inv_a, -1, -2), inv_a)


@register("_linalg_trmm", aliases=["linalg_trmm"])
def linalg_trmm(A, B, *, transpose=False, rightside=False, lower=True,
                alpha=1.0):
    """Triangular matrix multiply (reference: la_op.cc::trmm)."""
    tri = jnp.tril(A) if lower else jnp.triu(A)
    a = jnp.swapaxes(tri, -1, -2) if transpose else tri
    return alpha * (jnp.matmul(B, a) if rightside else jnp.matmul(a, B))


@register("_linalg_sumlogdiag", aliases=["linalg_sumlogdiag"])
def linalg_sumlogdiag(A):
    """Sum of log of the diagonal (reference: la_op.cc::sumlogdiag)."""
    return jnp.sum(jnp.log(jnp.diagonal(A, axis1=-2, axis2=-1)), axis=-1)


@register("_linalg_extractdiag", aliases=["linalg_extractdiag"])
def linalg_extractdiag(A, *, offset=0):
    return jnp.diagonal(A, offset=offset, axis1=-2, axis2=-1)


@register("_linalg_makediag", aliases=["linalg_makediag"])
def linalg_makediag(A, *, offset=0):
    return jnp.vectorize(lambda v: jnp.diag(v, k=offset),
                         signature="(n)->(m,m)")(A)


@register("_linalg_inverse", aliases=["linalg_inverse"])
def linalg_inverse(A):
    return jnp.linalg.inv(A)


@register("_linalg_det", aliases=["linalg_det"])
def linalg_det(A):
    return jnp.linalg.det(A)


@register("_linalg_slogdet", aliases=["linalg_slogdet"], num_outputs=2)
def linalg_slogdet(A):
    sign, logabsdet = jnp.linalg.slogdet(A)
    return sign, logabsdet


# ---------------------------------------------------------------------------
# init ops (reference: src/operator/tensor/init_op.cc)
# ---------------------------------------------------------------------------


@register("zeros_like")
def zeros_like(data):
    return jnp.zeros_like(data)


@register("ones_like")
def ones_like(data):
    return jnp.ones_like(data)


@register("_zeros", aliases=["zeros"])
def _zeros(*, shape=(), dtype="float32"):
    return jnp.zeros(tuple(shape), dtype=jnp.dtype(dtype))


@register("_ones", aliases=["ones"])
def _ones(*, shape=(), dtype="float32"):
    return jnp.ones(tuple(shape), dtype=jnp.dtype(dtype))


@register("_full", aliases=["full"])
def _full(*, shape=(), value=0.0, dtype="float32"):
    return jnp.full(tuple(shape), value, dtype=jnp.dtype(dtype))


@register("_arange", aliases=["arange"])
def _arange(*, start=0.0, stop=None, step=1.0, repeat=1, dtype="float32"):
    out = jnp.arange(start, stop, step, dtype=jnp.dtype(dtype))
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return out


@register("_linspace", aliases=["linspace"])
def _linspace(*, start=0.0, stop=1.0, num=50, endpoint=True, dtype="float32"):
    return jnp.linspace(start, stop, num, endpoint=endpoint, dtype=jnp.dtype(dtype))


@register("_eye", aliases=["eye"])
def _eye(*, N=1, M=0, k=0, dtype="float32"):
    return jnp.eye(N, M if M > 0 else None, k=k, dtype=jnp.dtype(dtype))


@register("_contrib_arange_like", aliases=["arange_like"])
def arange_like(data, *, start=0.0, step=1.0, repeat=1, axis=None):
    if axis is None:
        n = data.size
        out = start + step * jnp.arange(n, dtype=jnp.float32)
        return out.reshape(data.shape)
    n = data.shape[axis]
    return start + step * jnp.arange(n, dtype=jnp.float32)


@register("diag")
def diag(data, *, k=0, axis1=0, axis2=1):
    if data.ndim == 1:
        return jnp.diag(data, k=k)
    return jnp.diagonal(data, offset=k, axis1=axis1, axis2=axis2)


@register("shape_array")
def shape_array(data):
    return jnp.asarray(data.shape, dtype=jnp.int64)


@register("size_array")
def size_array(data):
    return jnp.asarray([data.size], dtype=jnp.int64)


@register("zeros_without_dtype", aliases=["_zeros_without_dtype"])
def zeros_without_dtype(*, shape=(), dtype=-1):
    return jnp.zeros(tuple(shape), dtype=jnp.float32)


@register("_scatter_set_nd", aliases=["scatter_set_nd"])
def scatter_set_nd(lhs, rhs, indices, *, shape=()):
    # reference: src/operator/tensor/indexing_op.cc::_scatter_set_nd —
    # functional form: lhs with lhs[indices] = rhs (last writer wins)
    idx = tuple(indices.astype(jnp.int32)[i] for i in range(indices.shape[0]))
    return lhs.at[idx].set(rhs)


@register("fill_element_0index")
def fill_element_0index(lhs, mhs, rhs):
    # reference: src/operator/tensor/matrix_op.cc fill_element_0index —
    # lhs[i, rhs[i]] = mhs[i] along axis 1
    rows = jnp.arange(lhs.shape[0])
    return lhs.at[rows, rhs.astype(jnp.int32)].set(mhs.astype(lhs.dtype))


@register("choose_element_0index")
def choose_element_0index(lhs, rhs):
    # reference: matrix_op.cc choose_element_0index — lhs[i, rhs[i]]
    rows = jnp.arange(lhs.shape[0])
    return lhs[rows, rhs.astype(jnp.int32)]


@register("_linalg_maketrian", aliases=["linalg_maketrian"])
def linalg_maketrian(data, *, offset=0, lower=True):
    """reference: src/operator/tensor/la_op.cc maketrian — pack a
    (..., n*(n+1)/2) vector into a (..., n, n) triangular matrix."""
    import math

    if offset != 0:
        raise NotImplementedError(
            "linalg_maketrian: offset != 0 is not implemented "
            "(SURVEY.md operator inventory, la_op.cc tail)")
    m = data.shape[-1]
    n = int((math.isqrt(8 * m + 1) - 1) // 2)
    if n * (n + 1) // 2 != m:
        raise ValueError(
            f"linalg_maketrian: last dim {m} is not a triangular number")
    if lower:
        r, c = jnp.tril_indices(n)
    else:
        r, c = jnp.triu_indices(n)
    out = jnp.zeros(data.shape[:-1] + (n, n), dtype=data.dtype)
    return out.at[..., r, c].set(data)


@register("_linalg_extracttrian", aliases=["linalg_extracttrian"])
def linalg_extracttrian(data, *, offset=0, lower=True):
    """reference: la_op.cc extracttrian — unpack the triangle of a
    (..., n, n) matrix into a (..., n*(n+1)/2) vector."""
    if offset != 0:
        raise NotImplementedError(
            "linalg_extracttrian: offset != 0 is not implemented "
            "(SURVEY.md operator inventory, la_op.cc tail)")
    n = data.shape[-1]
    if lower:
        r, c = jnp.tril_indices(n)
    else:
        r, c = jnp.triu_indices(n)
    return data[..., r, c]
