"""Operator library (L4): pure-JAX implementations behind the op registry.

Reference: ``src/operator/`` — see SURVEY.md §2.1. Modules here register
ops by MXNet name; both ``mx.nd`` and ``mx.sym`` dispatch through
``mxnet_tpu.ops.registry``.
"""
from . import registry  # noqa: F401
from .registry import get_op, has_op, list_ops  # noqa: F401
