"""Neural-network operators.

Reference: ``src/operator/nn/`` — ``convolution.cc``, ``fully_connected.cc``,
``batch_norm.cc``, ``layer_norm.cc``, ``pooling.cc``, ``activation.cc``,
``softmax.cc``, ``dropout.cc``, ``deconvolution.cc``; plus
``src/operator/softmax_output.cc``, ``leaky_relu.cc``, ``instance_norm.cc``,
``l2_normalization.cc``, ``embedding`` from ``indexing_op.cc``.

TPU mapping: Convolution/FullyConnected lower to ``lax.conv_general_dilated``
/ ``lax.dot_general`` which XLA tiles onto the MXU; elementwise epilogues
(bias, activation) fuse into the matmul automatically under jit.
"""
from __future__ import annotations

from functools import partial as _partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as _np

from .registry import attr, register

# ---------------------------------------------------------------------------
# dense / conv
# ---------------------------------------------------------------------------


@register("FullyConnected", aliases=["fully_connected"], attrs=[
    attr("num_hidden", int, "Number of output hidden units.", low=0),
    attr("no_bias", bool, "Whether to disable the bias term."),
    attr("flatten", bool,
         "Flatten trailing input dims into one (MXNet default) or apply "
         "the projection to the last axis only."),
])
def fully_connected(data, weight, bias=None, *, num_hidden=0, no_bias=False, flatten=True):
    # reference: src/operator/nn/fully_connected.cc :: FullyConnectedCompute
    if flatten and data.ndim > 2:
        data = data.reshape(data.shape[0], -1)
    out = jnp.matmul(data, weight.T.astype(data.dtype))
    if not no_bias and bias is not None:
        out = out + bias.astype(out.dtype)
    return out


def _tuplize(v, n):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(v)
    if len(v) == 1:
        return v * n
    return v


def _conv_dnums(nd, layout=None):
    # MXNet layouts: NCW/NCHW/NCDHW (default) or NWC/NHWC/NDHWC
    # (channels-last — the TPU-preferred internal layout; XLA then needs no
    # activation relayout around the conv, see SURVEY.md §7.2 "fusion
    # audit"). Weights stay OIHW-style in BOTH cases so checkpoints are
    # layout-independent; XLA relayouts the (small) filter, not the
    # activations.
    spatial = "DHW"[-nd:] if nd <= 3 else None
    lhs = ("N" + spatial + "C") if (layout and layout.endswith("C")) \
        else ("NC" + spatial)
    rhs = "OI" + spatial
    return jax.lax.conv_dimension_numbers(
        (1,) * (nd + 2), (1,) * (nd + 2), (lhs, rhs, lhs))


def _channel_axis(layout, ndim):
    return (ndim - 1) if (layout and layout.endswith("C")) else 1


@register("Convolution", aliases=["convolution"], attrs=[
    attr("kernel", tuple, "Spatial kernel size, e.g. (3, 3)."),
    attr("stride", tuple, "Strides per spatial dim (default 1).", low=1),
    attr("dilate", tuple, "Dilation per spatial dim (default 1).", low=1),
    attr("pad", tuple, "Zero padding per spatial dim.", low=0),
    attr("num_filter", int, "Number of output channels.", low=1),
    attr("num_group", int, "Grouped-convolution group count.", low=1),
    attr("no_bias", bool, "Whether to disable the bias term."),
    attr("layout", str, "Input/output layout; channels-last is the "
         "TPU-preferred internal layout.",
         choices=("NCW", "NCHW", "NCDHW", "NWC", "NHWC", "NDHWC")),
])
def convolution(data, weight, bias=None, *, kernel=(), stride=(), dilate=(),
                pad=(), num_filter=1, num_group=1, no_bias=False,
                layout=None, workspace=1024, cudnn_tune=None, cudnn_off=False):
    # reference: src/operator/nn/convolution.cc :: ConvolutionCompute
    nd = len(kernel)
    stride = _tuplize(stride or 1, nd)
    dilate = _tuplize(dilate or 1, nd)
    pad = _tuplize(pad or 0, nd)
    dnums = _conv_dnums(nd, layout)
    out = _conv_core(data, weight.astype(data.dtype), stride,
                     [(p, p) for p in pad], dilate, dnums, num_group,
                     layout, kernel)
    out = out.astype(data.dtype)
    if not no_bias and bias is not None:
        bshape = [1] * out.ndim
        bshape[_channel_axis(layout, out.ndim)] = bias.shape[0]
        out = out + bias.astype(out.dtype).reshape(bshape)
    return out


def _conv_s2d(x, w, kernel):
    """Stride-2 large-kernel conv via space-to-depth re-indexing (exact).

    out[ho] = sum_a x[2*ho + a - pad] * W[a] splits by input parity r:
    a = 2*alpha + r + pad, so the same sum is a STRIDE-1 conv over the
    s2d-packed input (phase r becomes a channel) with ceil-halved taps.
    MXU win: contraction depth grows 4x (3->12 channels for the ResNet
    stem, where C=3 left the systolic array ~85% idle; PERF.md round 4)
    and the strided-dW backward formulation disappears — autodiff of this
    composite IS the transformed backward.
    """
    n, h, w_, c = x.shape
    o = w.shape[0]

    def geom(k):
        pad = (k - 1) // 2
        alpha_lo = min(-((pad + r) // 2) for r in (0, 1))
        alpha_hi = max((k - 1 - pad - r) // 2 for r in (0, 1))
        taps = alpha_hi - alpha_lo + 1
        lpad = -(2 * alpha_lo + pad)  # 0 or 1
        return pad, alpha_lo, alpha_hi, taps, lpad

    kh, kw = kernel
    _, alo_h, ahi_h, th, lh = geom(kh)
    _, alo_w, ahi_w, tw, lw = geom(kw)
    wp = jnp.pad(w, ((0, 0), (0, 0), (lh, 2 * th - kh - lh),
                     (lw, 2 * tw - kw - lw)))
    w2 = wp.reshape(o, c, th, 2, tw, 2).transpose(0, 3, 5, 1, 2, 4)
    w2 = w2.reshape(o, 4 * c, th, tw)
    x2 = x.reshape(n, h // 2, 2, w_ // 2, 2, c).transpose(0, 1, 3, 2, 4, 5)
    x2 = x2.reshape(n, h // 2, w_ // 2, 4 * c)
    dn = jax.lax.conv_dimension_numbers(
        x2.shape, w2.shape, ("NHWC", "OIHW", "NHWC"))
    return jax.lax.conv_general_dilated(
        x2, w2, (1, 1), [(-alo_h, ahi_h), (-alo_w, ahi_w)],
        dimension_numbers=dn)


@_partial(jax.custom_vjp, nondiff_argnums=(2,))
def _conv1x1_strided_dot(x, w, stride):
    """Stride-(sh,sw) 1x1 NHWC conv: strided slice + MXU dot.

    dX zero-interleaves the small cotangent matmul back onto the input
    grid by pad+reshape instead of XLA's lhs-dilated scatter-conv
    (~2.5x its bandwidth floor on the ResNet downsample shapes).
    """
    sh, sw = stride
    xs = x[:, ::sh, ::sw, :]
    w2 = w.reshape(w.shape[0], w.shape[1]).astype(x.dtype)
    out = jax.lax.dot_general(xs, w2, (((3,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)
    return out.astype(x.dtype)


def _conv1x1_strided_fwd(x, w, stride):
    return _conv1x1_strided_dot(x, w, stride), (x, w)


def _conv1x1_strided_bwd(stride, res, dy):
    x, w = res
    sh, sw = stride
    n, h, w_, c = x.shape
    w2 = w.reshape(w.shape[0], w.shape[1]).astype(dy.dtype)
    dxs = jax.lax.dot_general(dy, w2, (((3,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32
                              ).astype(x.dtype)
    # zero-interleave (N,Ho,Wo,C) -> (N,H,W,C): pad the phase dims
    dx = jnp.pad(dxs[:, :, None, :, None, :],
                 ((0, 0), (0, 0), (0, sh - 1), (0, 0), (0, sw - 1), (0, 0))
                 ).reshape(n, h, w_, c)
    xs = x[:, ::sh, ::sw, :]
    dw = jax.lax.dot_general(dy, xs, (((0, 1, 2), (0, 1, 2)), ((), ())),
                             preferred_element_type=jnp.float32)
    return dx, dw.reshape(w.shape).astype(w.dtype)


_conv1x1_strided_dot.defvjp(_conv1x1_strided_fwd, _conv1x1_strided_bwd)


@jax.custom_vjp
def _conv1x1_dot(x, w):
    """Stride-1 1x1 NHWC conv as a dot_general, with dot-formulated VJPs.

    x: (N, H, W, C), w: (O, C, 1, 1) [OIHW weight convention kept so
    checkpoints stay layout-independent]. Forward contracts C; dX and dW
    are the transposed contractions — all three run on the MXU as dots,
    bypassing XLA:TPU's conv-backward algorithm selection (measured ~40%
    of roofline on the same shapes inside ResNet-50; PERF.md round 4).
    f32 accumulation, output cast back to the input dtype.
    """
    # NO preferred_element_type=f32: the TPU MXU accumulates bf16 dots in
    # f32 natively and rounds on output, but an explicit f32 preferred
    # type SURVIVES XLA's dot->conv canonicalization — the round-5 HLO
    # byte audit found ~14 GB/step of f32[256,56,56,256]-class conv
    # outputs materialized in HBM (2x the bytes of the bf16 tensors the
    # 3x3 convs emit), with the .astype living in the consumer fusion
    w2 = w.reshape(w.shape[0], w.shape[1]).astype(x.dtype)
    out = jax.lax.dot_general(x, w2, (((3,), (1,)), ((), ())))
    return out.astype(x.dtype)


def _conv1x1_dot_fwd(x, w):
    return _conv1x1_dot(x, w), (x, w)


def _conv1x1_dot_bwd(res, dy):
    x, w = res
    w2 = w.reshape(w.shape[0], w.shape[1]).astype(dy.dtype)
    # dX[n,h,w,c] = sum_o dy[n,h,w,o] * W[o,c] — no preferred f32 (see
    # forward note: it would materialize f32 dX tensors after dot->conv
    # canonicalization)
    dx = jax.lax.dot_general(
        dy, w2, (((3,), (0,)), ((), ()))).astype(x.dtype)
    # dW[o,c] = sum_{n,h,w} dy[n,h,w,o] * x[n,h,w,c]
    dw = jax.lax.dot_general(
        dy, x, (((0, 1, 2), (0, 1, 2)), ((), ())),
        preferred_element_type=jnp.float32)
    return dx, dw.reshape(w.shape).astype(w.dtype)


_conv1x1_dot.defvjp(_conv1x1_dot_fwd, _conv1x1_dot_bwd)


def _conv_core(data, weight, stride, pads, dilate, dnums, groups, layout,
               kernel):
    """conv_general_dilated, with a custom dW backward on eligible shapes.

    XLA:TPU derives dW as a conv whose 'kernel' is the (large) dy tensor —
    measured at ~38% of roofline across ResNet-50's layers (PERF.md round
    3; VERDICT r3 #3). MXNET_TPU_CONV_DW=patches switches eligible convs
    (2-D, group-1, undilated, channels-last) to an explicit im2col dW:
    gather input patches (conv_general_dilated_patches), contract
    (N·Ho·Wo) x (C·kh·kw) against (N·Ho·Wo) x O in ONE MXU dot_general;
    dX keeps XLA's transposed-conv rule.

    Measured END-TO-END on ResNet-50 batch 256 (round 4): the patches
    formulation is 4x SLOWER (615 vs 2,324 img/s) — the materialized
    patch tensors (9x activation bytes for 3x3 convs) turn the step
    HBM-bound, and XLA cannot fuse the gather into the contraction. An
    isolated chained-scan microbench (tools/convbwd_bench.py) said the
    opposite (vjp-dW 12-46x slower there), i.e. the scan context poisons
    XLA's conv-bwd algorithm choice; trust only in-model traces. Kept
    env-gated for experiments; default = XLA's own backward.
    """
    import os

    def conv(x, w):
        return jax.lax.conv_general_dilated(
            x, w, window_strides=stride, padding=pads,
            rhs_dilation=dilate, dimension_numbers=dnums,
            feature_group_count=groups,
            # NOTE: no preferred_element_type=f32 — the TPU MXU
            # accumulates bf16 convs in f32 natively, and an explicit f32
            # output breaks the conv transpose (VJP) rule's dtype
            # agreement.
        )

    # ResNet-stem-shaped convs (large kernel, stride 2, <=4 input channels)
    # run the MXU at ~15% of roofline: contraction channels of 3 leave the
    # systolic array idle, and the strided dW formulation is worse still.
    # Space-to-depth is the exact re-indexing fix: s2d(2) the input
    # (C -> 4C), zero-pad the kernel to even taps, and the same arithmetic
    # becomes a stride-1 conv with 4x the contraction depth. Exact for
    # fwd AND both backward passes (it is a pure re-indexing, so autodiff
    # through the reshape/conv composite is the transformed backward).
    if (len(kernel) == 2 and tuple(stride) == (2, 2)
            and groups == 1 and all(d == 1 for d in dilate)
            and not isinstance(pads, str)
            and bool(layout) and layout.endswith("C")
            and data.ndim == 4 and data.shape[-1] <= 4
            and kernel[0] >= 5 and kernel[1] >= 5
            and all(tuple(p) == ((k - 1) // 2,) * 2
                    for p, k in zip(pads, kernel))
            and data.shape[1] % 2 == 0 and data.shape[2] % 2 == 0
            and os.environ.get("MXNET_TPU_CONV_S2D", "1") == "1"):
        return _conv_s2d(data, weight, kernel)

    # Strided 1x1 convs as strided SLICE + matmul, dX zero-interleaved by
    # pad+reshape instead of XLA's lhs-dilated scatter-conv. Measured
    # END-TO-END in ResNet-50 (round 4): a 4.5% REGRESSION (2,465 vs
    # 2,585 img/s) — the materialized slice/pad intermediates cost more
    # than the scatter-conv formulation they replace, mirroring the
    # round-4 patches-dW lesson that isolated-op roofline math loses to
    # XLA's fusion once the op sits inside a real step. Kept opt-in for
    # experiments.
    if (tuple(kernel) == (1, 1) and len(stride) == 2
            and max(stride) > 1 and groups == 1
            and all(d == 1 for d in dilate)
            and not isinstance(pads, str)
            and all(tuple(p) == (0, 0) for p in pads)
            and bool(layout) and layout.endswith("C")
            and data.ndim == 4
            and data.shape[1] % stride[0] == 0
            and data.shape[2] % stride[1] == 0
            and os.environ.get("MXNET_TPU_CONV1X1_STRIDED_DOT", "0") == "1"):
        return _conv1x1_strided_dot(data, weight, tuple(stride))

    # Stride-1 1x1 channels-last convs ARE matmuls: formulate fwd/dW/dX as
    # explicit dot_generals so XLA:TPU's matmul path (not its conv-backward
    # algorithm selection) runs them. Round-4 trace: the 1x1 dX/dW conv
    # formulations sat at ~40% of the matmul roofline inside the ResNet-50
    # step (PERF.md round 4, conv-attribution table); a dot never enters
    # conv algorithm selection at all.
    if (tuple(kernel) == (1, 1) and tuple(stride) == (1, 1)
            and groups == 1 and all(d == 1 for d in dilate)
            and not isinstance(pads, str)
            and all(tuple(p) == (0, 0) for p in pads)
            and bool(layout) and layout.endswith("C")
            and data.ndim == 4
            and os.environ.get("MXNET_TPU_CONV1X1_DOT", "1") == "1"):
        return _conv1x1_dot(data, weight)

    eligible = (len(kernel) == 2 and groups == 1
                and all(d == 1 for d in dilate)
                and bool(layout) and layout.endswith("C")
                and os.environ.get("MXNET_TPU_CONV_DW", "vjp")
                == "patches")
    if not eligible:
        return conv(data, weight)

    kh, kw = kernel

    @jax.custom_vjp
    def f(x, w):
        return conv(x, w)

    def f_fwd(x, w):
        return conv(x, w), (x, w)

    def f_bwd(res, dy):
        x, w = res
        _, pull_x = jax.vjp(lambda x_: conv(x_, w), x)
        (dx,) = pull_x(dy)
        # dW via im2col: patches (N,Ho,Wo, C*kh*kw) — feature order is
        # (C, kh, kw), per conv_general_dilated_patches — against
        # dy (N,Ho,Wo,O), contracted over all positions at once
        patches = jax.lax.conv_general_dilated_patches(
            x, (kh, kw), stride, pads,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        n, ho, wo, _ = patches.shape
        cin = x.shape[-1]
        dw = jax.lax.dot_general(
            patches.reshape(n * ho * wo, cin * kh * kw),
            dy.reshape(n * ho * wo, -1),
            (((0,), (0,)), ((), ())))
        # (C*kh*kw, O) -> (O, C, kh, kw) == the OIHW weight layout
        dw = dw.reshape(cin, kh, kw, -1).transpose(3, 0, 1, 2)
        return dx, dw.astype(w.dtype)

    f.defvjp(f_fwd, f_bwd)
    return f(data, weight)


@register("Deconvolution", aliases=["deconvolution"])
def deconvolution(data, weight, bias=None, *, kernel=(), stride=(), dilate=(),
                  pad=(), adj=(), num_filter=1, num_group=1, no_bias=True,
                  target_shape=(), layout=None, workspace=1024,
                  cudnn_tune=None, cudnn_off=False):
    # reference: src/operator/nn/deconvolution.cc — conv transpose.
    nd = len(kernel)
    stride = _tuplize(stride or 1, nd)
    dilate = _tuplize(dilate or 1, nd)
    pad = _tuplize(pad or 0, nd)
    adj = _tuplize(adj or 0, nd)
    spatial = "DHW"[-nd:]
    lhs = ("N" + spatial + "C") if (layout and layout.endswith("C")) \
        else ("NC" + spatial)
    dn = jax.lax.conv_dimension_numbers(
        data.shape, weight.shape, (lhs, "IO" + spatial, lhs)
    )
    # conv_transpose with MXNet padding semantics:
    # out = (in-1)*stride - 2*pad + dilate*(k-1) + 1 + adj
    padding = []
    for i in range(nd):
        k_eff = dilate[i] * (kernel[i] - 1) + 1
        lo = k_eff - 1 - pad[i]
        hi = k_eff - 1 - pad[i] + adj[i]
        padding.append((lo, hi))
    if num_group > 1:
        # lax.conv_transpose has no group support; the equivalent
        # lhs-dilated conv does. Deconv weight (I, O/g, k, k) becomes a
        # conv weight (O, I/g, k, k) by per-group channel transpose only.
        g = num_group
        i_ch = weight.shape[0]
        og = weight.shape[1]
        wt = weight.reshape((g, i_ch // g, og) + tuple(weight.shape[2:]))
        wt = jnp.swapaxes(wt, 1, 2).reshape((g * og, i_ch // g)
                                            + tuple(weight.shape[2:]))
        # NO spatial flip: matches lax.conv_transpose(transpose_kernel=
        # False), the convention the ungrouped path (and MXNet) uses
        dn2 = jax.lax.conv_dimension_numbers(
            data.shape, wt.shape, (lhs, "OI" + spatial, lhs))
        out = jax.lax.conv_general_dilated(
            data, wt.astype(data.dtype), window_strides=(1,) * nd,
            padding=padding, lhs_dilation=stride, rhs_dilation=dilate,
            dimension_numbers=dn2, feature_group_count=g)
    else:
        out = jax.lax.conv_transpose(
            data, weight.astype(data.dtype), strides=stride,
            padding=padding, rhs_dilation=dilate, dimension_numbers=dn,
            transpose_kernel=False,
        )
    out = out.astype(data.dtype)
    if not no_bias and bias is not None:
        bshape = [1] * out.ndim
        bshape[_channel_axis(layout, out.ndim)] = bias.shape[0]
        out = out + bias.astype(out.dtype).reshape(bshape)
    return out


# ---------------------------------------------------------------------------
# pooling
# ---------------------------------------------------------------------------


@register("Pooling", aliases=["pooling"], attrs=[
    attr("kernel", tuple, "Pooling window size."),
    attr("pool_type", str, "Pooling reduction.",
         choices=("max", "avg", "sum", "lp")),
    attr("stride", tuple, "Window strides (default 1).", low=1),
    attr("pad", tuple, "Zero padding per spatial dim.", low=0),
    attr("global_pool", bool, "Pool over the whole spatial extent."),
    attr("pooling_convention", str, "Output-size rounding rule.",
         choices=("valid", "full", "same")),
    attr("p_value", int, "p of the Lp pooling norm.", low=1),
    attr("layout", str, "Input layout.",
         choices=("NCW", "NCHW", "NCDHW", "NWC", "NHWC", "NDHWC")),
])
def pooling(data, *, kernel=(), pool_type="max", stride=(), pad=(),
            global_pool=False, pooling_convention="valid", count_include_pad=True,
            cudnn_off=False, p_value=2, layout=None):
    # reference: src/operator/nn/pooling.cc :: PoolingCompute
    # layout: channels-first (default) or channels-last ("NHWC"/"NWC"/
    # "NDHWC") — spatial window axes shift accordingly
    nd = data.ndim - 2
    channels_last = bool(layout) and layout.endswith("C")
    spatial0 = 1 if channels_last else 2
    if global_pool:
        ax = tuple(range(spatial0, spatial0 + nd))
        if pool_type == "max":
            return jnp.max(data, axis=ax, keepdims=True)
        if pool_type in ("avg", "sum"):
            r = jnp.mean if pool_type == "avg" else jnp.sum
            return r(data, axis=ax, keepdims=True)
        if pool_type == "lp":
            return jnp.power(
                jnp.sum(jnp.power(jnp.abs(data), p_value), axis=ax, keepdims=True),
                1.0 / p_value)
    kernel = _tuplize(kernel, nd)
    stride = _tuplize(stride or 1, nd)
    pad = _tuplize(pad or 0, nd)
    if channels_last:
        window = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
    else:
        window = (1, 1) + kernel
        strides = (1, 1) + stride

    def pads_for(convention):
        spatial = []
        for i in range(nd):
            if convention == "same":
                # TF-style SAME: out = ceil(in / stride); symmetric split
                # with the extra cell at the end. Explicit pad is part of
                # the convention, not additive (reference pooling.cc
                # requires pad=0 with convention=same).
                size = data.shape[spatial0 + i]
                out = -(-size // stride[i])
                total = max((out - 1) * stride[i] + kernel[i] - size, 0)
                lo = total // 2
                hi = total - lo
            else:
                lo = hi = pad[i]
                if convention == "full":
                    # ceil instead of floor output size: extra hi padding
                    size = data.shape[spatial0 + i] + 2 * pad[i] - kernel[i]
                    rem = size % stride[i]
                    if rem != 0:
                        hi += stride[i] - rem
            spatial.append((lo, hi))
        if channels_last:
            return [(0, 0)] + spatial + [(0, 0)]
        return [(0, 0), (0, 0)] + spatial

    if pooling_convention == "same" and any(p != 0 for p in pad):
        raise ValueError(
            "Pooling: pooling_convention='same' requires pad=0 "
            "(reference: src/operator/nn/pooling.cc parameter check)")

    padding = pads_for(pooling_convention)
    if pool_type == "max":
        # fixed-width init scalar: a bare Python int promotes to i64
        # under jax_enable_x64 and reduce_window rejects the mismatch
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) \
            else _np.dtype(data.dtype).type(jnp.iinfo(data.dtype).min)
        return jax.lax.reduce_window(data, init, jax.lax.max, window, strides, padding)
    if pool_type in ("avg", "sum"):
        summed = jax.lax.reduce_window(data, 0.0, jax.lax.add, window, strides, padding)
        if pool_type == "sum":
            return summed
        if count_include_pad:
            denom = 1
            for k in kernel:
                denom *= k
            return summed / denom
        ones = jnp.ones_like(data)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, padding)
        return summed / counts
    if pool_type == "lp":
        powed = jax.lax.reduce_window(
            jnp.power(jnp.abs(data), p_value), 0.0, jax.lax.add, window, strides, padding)
        return jnp.power(powed, 1.0 / p_value)
    raise ValueError(f"unknown pool_type {pool_type}")


@register("ROIPooling")
def roi_pooling(data, rois, *, pooled_size=(), spatial_scale=1.0):
    """reference: src/operator/roi_pooling.cc — quantized-bin max pooling.

    Bin i spans [floor(i*rh/ph), ceil((i+1)*rh/ph)) like the reference
    (bins may overlap by one row/col). Dense masked-max formulation:
    data-dependent bin edges become boolean masks over the feature map, a
    per-axis reduction each — no dynamic shapes, XLA-friendly.
    """
    ph, pw = pooled_size
    n, c, h_, w_ = data.shape

    def one(roi):
        b = roi[0].astype(jnp.int32)
        # reference round() is half-AWAY-from-zero; coords are >= 0 so
        # floor(x + 0.5) reproduces it (jnp.round is half-to-even)
        x1 = jnp.floor(roi[1] * spatial_scale + 0.5).astype(jnp.int32)
        y1 = jnp.floor(roi[2] * spatial_scale + 0.5).astype(jnp.int32)
        x2 = jnp.floor(roi[3] * spatial_scale + 0.5).astype(jnp.int32)
        y2 = jnp.floor(roi[4] * spatial_scale + 0.5).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1).astype(jnp.float32)
        rw = jnp.maximum(x2 - x1 + 1, 1).astype(jnp.float32)
        img = jnp.take(data, b, axis=0)  # (C, H, W)
        hs = jnp.arange(h_)[:, None]
        ws = jnp.arange(w_)[:, None]
        iy = jnp.arange(ph)[None]
        ix = jnp.arange(pw)[None]
        hstart = y1 + jnp.floor(iy * rh / ph).astype(jnp.int32)
        hend = y1 + jnp.ceil((iy + 1) * rh / ph).astype(jnp.int32)
        wstart = x1 + jnp.floor(ix * rw / pw).astype(jnp.int32)
        wend = x1 + jnp.ceil((ix + 1) * rw / pw).astype(jnp.int32)
        ymask = (hs >= hstart) & (hs < hend) & (hs >= 0) & (hs < h_)
        xmask = (ws >= wstart) & (ws < wend) & (ws >= 0) & (ws < w_)
        neg = jnp.array(-jnp.inf, dtype=jnp.float32)
        # reduce W first: (C, H, pw), then H: (C, ph, pw)
        tmp = jnp.max(jnp.where(xmask[None, None], img.astype(
            jnp.float32)[..., None], neg), axis=2)
        out = jnp.max(jnp.where(ymask[None, :, :, None],
                                tmp[:, :, None, :], neg), axis=1)
        return jnp.where(jnp.isfinite(out), out, 0.0).astype(data.dtype)

    return jax.vmap(one)(rois.astype(jnp.float32))


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------


@register("BatchNorm", aliases=["batch_norm"], pass_training_flag=True,
          attrs=[
    attr("eps", float, "Numerical-stability epsilon added to variance.",
         low=0.0),
    attr("momentum", float, "Moving-average momentum.", low=0.0, high=1.0),
    attr("fix_gamma", bool, "Treat gamma as fixed at 1."),
    attr("use_global_stats", bool,
         "Normalize with moving stats even in training."),
    attr("axis", int, "Channel axis (1 = channels-first, -1 = last)."),
])
def batch_norm(data, gamma, beta, moving_mean, moving_var, *, eps=1e-3,
               momentum=0.9, fix_gamma=True, use_global_stats=False,
               output_mean_var=False, axis=1, cudnn_off=False,
               min_calib_range=None, max_calib_range=None, _training=False):
    """reference: src/operator/nn/batch_norm.cc :: BatchNormCompute.

    In training mode returns (out, batch_mean, batch_var) so the caller
    (gluon BatchNorm block / CachedOp aux-state threading) can update the
    moving statistics functionally — the TPU-native replacement for MXNet's
    in-place aux-state mutation. In inference mode returns just `out`
    (matching mx.nd.BatchNorm's single visible output).
    """
    axis = axis % data.ndim
    bshape = [1] * data.ndim
    bshape[axis] = data.shape[axis]
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    use_batch_stats = _training and not use_global_stats
    if use_batch_stats:
        out, mean, var = _bn_train(axis, float(eps), data, g, beta)
        return out, mean, var
    reduce_axes = tuple(i for i in range(data.ndim) if i != axis)
    x32 = data.astype(jnp.float32)
    mean, var = moving_mean.astype(jnp.float32), moving_var.astype(jnp.float32)
    inv = jax.lax.rsqrt(var + eps)
    scale = g.astype(jnp.float32) * inv
    bias = beta.astype(jnp.float32) - mean * scale
    out = (x32 * scale.reshape(bshape) + bias.reshape(bshape)).astype(data.dtype)
    if output_mean_var:
        return out, mean, var
    return out


def _bn_stats(x, axis, eps):
    """Per-channel (mean, var, rsqrt(var+eps)).

    Half-precision inputs use one-traversal moments (E[x^2]-E[x]^2, both
    reduced in the same fused f32 loop): the f32 cancellation error,
    ~1e-7*(mean/std)^2 relative, is subdominant to the input's own bf16
    quantization until mean/std exceeds ~300. f32 inputs keep the exact
    centered two-pass (jnp.var) — they carry no quantization floor to
    hide behind, and the extra traversal only matters on the bf16 hot
    path.
    """
    reduce_axes = tuple(i for i in range(x.ndim) if i != axis)
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=reduce_axes)
    if x.dtype == jnp.float32 or x.dtype == jnp.float64:
        var = jnp.var(x32, axis=reduce_axes)
    else:
        sq = jnp.mean(x32 * x32, axis=reduce_axes)
        var = jnp.maximum(sq - mean * mean, 0.0)
    return mean, var, jax.lax.rsqrt(var + eps)


@_partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _bn_train(axis, eps, x, g, b):
    """Training-mode batch norm with a hand-derived backward.

    Autodiff through the statistics produces ~7 full-tensor reductions and
    a dozen f32 elementwise chains per layer (round-4 ResNet trace: the
    BN-backward arithmetic fused into the conv-dX fusions was the largest
    single cost bucket). The classic two-reduction backward needs only
    sum(dy) and sum(dy*xhat) — which are exactly dbeta and dgamma.

    The (mean, var) outputs are statistics for the moving-average update
    (MXNet aux states, reference: src/operator/nn/batch_norm.cc — aux
    outputs carry no gradient); their cotangents are ignored.
    """
    out, mean, var, _ = _bn_train_math(axis, eps, x, g, b)
    return out, mean, var


def _bn_train_math(axis, eps, x, g, b):
    bshape = [1] * x.ndim
    bshape[axis] = x.shape[axis]
    mean, var, inv = _bn_stats(x, axis, eps)
    scale = g.astype(jnp.float32) * inv
    bias = b.astype(jnp.float32) - mean * scale
    out = (x.astype(jnp.float32) * scale.reshape(bshape)
           + bias.reshape(bshape)).astype(x.dtype)
    return out, mean, var, inv


def _bn_train_fwd(axis, eps, x, g, b):
    out, mean, var, inv = _bn_train_math(axis, eps, x, g, b)
    return (out, mean, var), (x, g, b, mean, inv)


def _bn_train_bwd(axis, eps, res, cots):
    x, g, b, mean, inv = res
    dy = cots[0]  # stats cotangents (aux moving-average path) are zero
    reduce_axes = tuple(i for i in range(x.ndim) if i != axis)
    bshape = [1] * x.ndim
    bshape[axis] = x.shape[axis]
    n = 1
    for i in reduce_axes:
        n *= x.shape[i]
    dy32 = dy.astype(jnp.float32)
    xhat = (x.astype(jnp.float32) - mean.reshape(bshape)) * inv.reshape(bshape)
    dbeta = jnp.sum(dy32, axis=reduce_axes)
    dgamma = jnp.sum(dy32 * xhat, axis=reduce_axes)
    g32 = g.astype(jnp.float32)
    dx = ((g32 * inv / n).reshape(bshape)
          * (n * dy32 - dbeta.reshape(bshape) - xhat * dgamma.reshape(bshape))
          ).astype(x.dtype)
    return dx, dgamma.astype(g.dtype), dbeta.astype(b.dtype)


_bn_train.defvjp(_bn_train_fwd, _bn_train_bwd)


def _fused_ln_routable(data, axis):
    """True when the Pallas fused-LN kernel may take this call:
    MXNET_PALLAS_FUSED=1, last-axis norm, TPU execution platform and the
    row/lane shape gate (``fused_ln_supported``, the flash_supported
    twin). Checked per call — the env knob is a live switch."""
    from ..pallas_kernels.fused_layers import (fused_layers_enabled,
                                               fused_ln_supported)

    if not fused_layers_enabled():
        return False
    if axis not in (-1, data.ndim - 1):
        return False
    return fused_ln_supported(data)


@register("LayerNorm", aliases=["layer_norm"])
def layer_norm(data, gamma, beta, *, axis=-1, eps=1e-5, output_mean_var=False):
    # reference: src/operator/nn/layer_norm.cc
    if not output_mean_var and _fused_ln_routable(data, axis):
        # Pallas one-pass kernel (pallas_kernels/fused_layers.py): same
        # f32 statistics, custom_vjp backward recomputing xhat from the
        # saved (mean, rstd) rows instead of autodiff through the
        # reductions — the bandwidth-bound LN sweep from the PERF.md
        # batch-32 trace
        from .. import telemetry
        from ..pallas_kernels.fused_layers import fused_layer_norm

        telemetry.record_pallas_dispatch("fused_layer_norm")
        return fused_layer_norm(data, gamma, beta, eps=eps)
    x32 = data.astype(jnp.float32)
    mean = jnp.mean(x32, axis=axis, keepdims=True)
    var = jnp.var(x32, axis=axis, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    out = (x32 - mean) * inv
    bshape = [1] * data.ndim
    bshape[axis] = data.shape[axis]
    out = out * gamma.astype(jnp.float32).reshape(bshape) + beta.astype(jnp.float32).reshape(bshape)
    out = out.astype(data.dtype)
    if output_mean_var:
        return out, jnp.squeeze(mean, axis), jnp.squeeze(var, axis)
    return out


@register("_contrib_fused_layer_norm", aliases=["fused_layer_norm"],
          needs_rng=True, pass_training_flag=True,
          rng_gate=lambda attrs: bool(attrs.get("dropout"))
          and bool(attrs.get("_training")), attrs=[
    attr("eps", float, "Normalization epsilon.", low=0.0),
    attr("dropout", float, "Drop rate applied to ``data`` (not the "
         "residual) before the add+norm.", low=0.0, high=1.0),
])
def fused_layer_norm_op(rng, data, gamma, beta, residual=None, *,
                        eps=1e-5, dropout=0.0, _training=False):
    """Fused ``LayerNorm(dropout(data) + residual)`` over the last axis
    — the post-LN transformer cell's add+norm collapsed into one op
    (reference capability: transformer.cc's fused residual epilogues).

    Routed to the Pallas one-pass kernel under ``MXNET_PALLAS_FUSED=1``
    + shape/platform gates; otherwise the eager jnp composition runs
    with the SAME stateless position-hash dropout mask, so both routes
    drop identical elements for a given op key (the flash-attention
    dropout contract). Training-mode only dropout; the PRNG key is
    drawn only when it applies (rng_gate).
    """
    from ..pallas_kernels.fused_layers import (fused_layer_norm,
                                               fused_layer_norm_reference)

    p = float(dropout) if _training else 0.0
    seed = None
    if p > 0.0:
        from ..pallas_kernels.flash_attention import fold_key_seed

        seed = fold_key_seed(rng)
    if _fused_ln_routable(data, -1):
        from .. import telemetry

        telemetry.record_pallas_dispatch("fused_layer_norm")
        return fused_layer_norm(data, gamma, beta, residual, eps=eps,
                                dropout=p, seed=seed)
    return fused_layer_norm_reference(data, gamma, beta, residual,
                                      eps=eps, dropout=p, seed=seed)


@register("_contrib_fused_bias_gelu", aliases=["fused_bias_gelu"])
def fused_bias_gelu_op(data, bias):
    """Fused ``gelu(data + bias)`` (exact erf form) — the Dense matmul
    epilogue. Bit-identical to the eager pair (bias add in the matmul
    dtype, then ``Activation(act_type='gelu')``); under
    ``MXNET_PALLAS_FUSED=1`` + gates it runs as one Pallas VMEM pass
    whose backward recomputes the activation derivative instead of
    saving erf/cdf intermediates."""
    from ..pallas_kernels.fused_layers import (fused_bias_gelu,
                                               fused_bias_gelu_reference)

    if _fused_ln_routable(data, -1):
        from .. import telemetry

        telemetry.record_pallas_dispatch("fused_bias_gelu")
        return fused_bias_gelu(data, bias)
    return fused_bias_gelu_reference(data, bias)


@register("InstanceNorm")
def instance_norm(data, gamma, beta, *, eps=1e-3):
    ax = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=ax, keepdims=True)
    var = jnp.var(data, axis=ax, keepdims=True)
    out = (data - mean) * jax.lax.rsqrt(var + eps)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    return out * gamma.reshape(bshape) + beta.reshape(bshape)


@register("GroupNorm")
def group_norm(data, gamma, beta, *, num_groups=1, eps=1e-5):
    n, c = data.shape[:2]
    rest = data.shape[2:]
    x = data.reshape((n, num_groups, c // num_groups) + rest)
    ax = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=ax, keepdims=True)
    var = jnp.var(x, axis=ax, keepdims=True)
    x = (x - mean) * jax.lax.rsqrt(var + eps)
    x = x.reshape(data.shape)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    return x * gamma.reshape(bshape) + beta.reshape(bshape)


@register("LRN")
def lrn(data, *, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    # reference: src/operator/nn/lrn.cc — cross-channel local response norm
    sq = jnp.square(data)
    half = nsize // 2
    padded = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    acc = jnp.zeros_like(data)
    for i in range(nsize):
        acc = acc + padded[:, i : i + data.shape[1]]
    return data / jnp.power(knorm + alpha * acc / nsize, beta)


# ---------------------------------------------------------------------------
# activations / softmax
# ---------------------------------------------------------------------------


@register("Activation", aliases=["activation"])
def activation(data, *, act_type="relu"):
    # reference: src/operator/nn/activation.cc
    fns = {
        "relu": jax.nn.relu,
        "sigmoid": jax.nn.sigmoid,
        "tanh": jnp.tanh,
        "softrelu": jax.nn.softplus,
        "softsign": jax.nn.soft_sign,
        "silu": jax.nn.silu,
        "swish": jax.nn.silu,
        "gelu": lambda x: jax.nn.gelu(x, approximate=False),
        "gelu_tanh": lambda x: jax.nn.gelu(x, approximate=True),
        "mish": lambda x: x * jnp.tanh(jax.nn.softplus(x)),
    }
    return fns[act_type](data)


@register("LeakyReLU")
def leaky_relu(data, gamma=None, *, act_type="leaky", slope=0.25,
               lower_bound=0.125, upper_bound=0.334, _training=False):
    # reference: src/operator/leaky_relu.cc
    if act_type == "leaky":
        return jnp.where(data > 0, data, slope * data)
    if act_type == "prelu":
        g = gamma
        if g.ndim < data.ndim and g.size > 1:
            g = g.reshape((1, -1) + (1,) * (data.ndim - 2))
        return jnp.where(data > 0, data, g * data)
    if act_type == "elu":
        return jnp.where(data > 0, data, slope * jnp.expm1(data))
    if act_type == "selu":
        scale, alpha = 1.0507009873554805, 1.6732632423543772
        return scale * jnp.where(data > 0, data, alpha * jnp.expm1(data))
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "rrelu":
        s = (lower_bound + upper_bound) / 2.0
        return jnp.where(data > 0, data, s * data)
    raise ValueError(act_type)


@register("softmax")
def softmax_op(data, length=None, *, axis=-1, temperature=None, dtype=None, use_length=False):
    # reference: src/operator/nn/softmax.cc
    x = data if temperature in (None, 1.0) else data / temperature
    if use_length and length is not None:
        pos = jnp.arange(x.shape[axis])
        shape = [1] * x.ndim
        shape[axis] = x.shape[axis]
        mask = pos.reshape(shape) < length.reshape(
            [x.shape[i] if i == 0 else 1 for i in range(x.ndim)])
        x = jnp.where(mask, x, -jnp.inf)
    out = jax.nn.softmax(x, axis=axis)
    if use_length and length is not None:
        out = jnp.where(jnp.isnan(out), 0.0, out)
    return out.astype(jnp.dtype(dtype)) if dtype else out


@register("log_softmax")
def log_softmax_op(data, *, axis=-1, temperature=None, dtype=None, use_length=False):
    x = data if temperature in (None, 1.0) else data / temperature
    out = jax.nn.log_softmax(x, axis=axis)
    return out.astype(jnp.dtype(dtype)) if dtype else out


@register("softmin")
def softmin(data, *, axis=-1, temperature=None, dtype=None):
    return softmax_op(-data, axis=axis, temperature=temperature, dtype=dtype)


@register("masked_softmax")
def masked_softmax(data, mask, *, axis=-1, temperature=1.0,
                   normalize=True):
    """Softmax over positions where ``mask`` is true; masked positions
    get probability 0 (reference: src/operator/nn/masked_softmax.cc —
    fully-masked rows produce zeros, not NaN)."""
    m = mask.astype(bool)
    x = data if temperature in (None, 1.0) else data / temperature
    if not normalize:
        # upstream normalize=False: plain exp on kept positions
        return jnp.where(m, jnp.exp(x), 0.0).astype(data.dtype)
    neg = jnp.finfo(jnp.float32).min
    out = jax.nn.softmax(jnp.where(m, x.astype(jnp.float32), neg),
                         axis=axis)
    # a fully-masked row softmaxes the uniform min -> uniform probs;
    # zero them like the reference kernel does
    out = jnp.where(m, out, 0.0)
    return out.astype(data.dtype)


@register("masked_log_softmax")
def masked_log_softmax(data, mask, *, axis=-1, temperature=1.0):
    """log of masked_softmax; masked positions are -inf (reference:
    masked_softmax.cc::MaskedSoftmaxGrad's paired log variant)."""
    m = mask.astype(bool)
    x = data if temperature in (None, 1.0) else data / temperature
    neg = jnp.finfo(jnp.float32).min
    out = jax.nn.log_softmax(jnp.where(m, x.astype(jnp.float32), neg),
                             axis=axis)
    out = jnp.where(m, out, -jnp.inf)
    return out.astype(data.dtype)


def _make_softmax_output(grad_scale, ignore_label, use_ignore, smooth_alpha,
                         normalization):
    """Fused softmax + cross-entropy-gradient head. The backward IGNORES the
    incoming gradient and emits (prob - one_hot(label)) * grad_scale,
    normalized per the `normalization` attr ('null' | 'batch' | 'valid') —
    reference: src/operator/softmax_output-inl.h :: SoftmaxOutputBackward."""

    @jax.custom_vjp
    def _so(data, label):
        return jax.nn.softmax(data, axis=-1)

    def fwd(data, label):
        prob = jax.nn.softmax(data, axis=-1)
        return prob, (prob, label)

    def bwd(res, g):
        prob, label = res
        n_class = prob.shape[-1]
        onehot = jax.nn.one_hot(label.astype(jnp.int32), n_class, dtype=prob.dtype)
        if smooth_alpha:
            onehot = onehot * (1 - smooth_alpha) + smooth_alpha / (n_class - 1) * (1 - onehot)
        grad = prob - onehot
        valid = None
        if use_ignore:
            mask = (label != ignore_label).astype(prob.dtype)
            grad = grad * mask[..., None]
            valid = jnp.maximum(jnp.sum(mask), 1.0)
        if normalization == "valid":
            denom = valid if valid is not None else float(_np_prod(prob.shape[:-1]))
            grad = grad / denom
        elif normalization == "batch":
            grad = grad / float(prob.shape[0])
        grad = grad * grad_scale
        lgrad = (jnp.zeros_like(label, dtype=jax.dtypes.float0)
                 if jnp.issubdtype(label.dtype, jnp.integer) else jnp.zeros_like(label))
        return grad, lgrad

    _so.defvjp(fwd, bwd)
    return _so


def _np_prod(shape):
    n = 1
    for d in shape:
        n *= d
    return n


@register("SoftmaxOutput", aliases=["Softmax"])
def softmax_output(data, label, *, grad_scale=1.0, ignore_label=-1.0,
                   multi_output=False, use_ignore=False, preserve_shape=False,
                   normalization="null", out_grad=False, smooth_alpha=0.0):
    _so = _make_softmax_output(grad_scale, ignore_label, use_ignore,
                               smooth_alpha, normalization)
    if multi_output:
        # (n, c, d1, ...) -> softmax over axis 1
        x = jnp.moveaxis(data, 1, -1)
        return jnp.moveaxis(_so(x, label), -1, 1)
    if data.ndim > 2 and not preserve_shape:
        flat = data.reshape(data.shape[0], -1)
        return _so(flat, label).reshape(data.shape)
    return _so(data, label)


@register("make_loss", aliases=["MakeLoss"])
def make_loss(data, *, grad_scale=1.0, valid_thresh=0.0, normalization="null"):
    # reference: src/operator/make_loss.cc — identity fwd, grad = grad_scale
    @jax.custom_vjp
    def _ml(x):
        return x

    def fwd(x):
        return x, x.shape

    def bwd(shape, g):
        return (jnp.full(shape, grad_scale, dtype=jnp.float32),)

    _ml.defvjp(fwd, bwd)
    return _ml(data)


@register("BlockGrad", aliases=["stop_gradient"])
def block_grad(data):
    return jax.lax.stop_gradient(data)


# ---------------------------------------------------------------------------
# embedding / dropout
# ---------------------------------------------------------------------------


@register("Embedding")
def embedding(data, weight, *, input_dim=0, output_dim=0, dtype="float32",
              sparse_grad=False, _sparse_uid=None):
    # reference: src/operator/tensor/indexing_op.cc :: EmbeddingOpForward
    idx = data.astype(jnp.int32)
    if sparse_grad and _sparse_uid is not None:
        from ..parallel.sparse_grad import sparse_grad_active

        if sparse_grad_active():
            # row-sparse gradient: the custom VJP logs (rows, dY) into
            # the active scope and the train step does a lazy row update
            # — the dense (vocab, dim) cotangent is never consumed
            return _sparse_lookup(weight, idx, _sparse_uid)
    return jnp.take(weight, idx, axis=0)


import functools as _functools

import numpy as _np_mod


@_functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _sparse_lookup(weight, idx, uid):
    return jnp.take(weight, idx, axis=0)


def _sparse_lookup_fwd(weight, idx, uid):
    return jnp.take(weight, idx, axis=0), (idx, weight)


def _sparse_lookup_bwd(uid, res, g):
    from ..parallel.sparse_grad import log_sparse_grad

    idx, weight = res
    log_sparse_grad(uid, idx, g)
    # symbolic-zero dense cotangent: dead unless the weight also feeds a
    # dense-grad op, which the sparse path forbids (see sparse_grad.py)
    return (jnp.zeros_like(weight),
            _np_mod.zeros(idx.shape, jax.dtypes.float0))


_sparse_lookup.defvjp(_sparse_lookup_fwd, _sparse_lookup_bwd)


@register("Dropout", aliases=["dropout"], needs_rng=True,
          pass_training_flag=True, attrs=[
    attr("p", float, "Fraction of units dropped.", low=0.0, high=1.0),
    attr("mode", str, "When to apply dropout.",
         choices=("training", "always")),
])
def dropout_op(rng, data, *, p=0.5, mode="training", axes=(), cudnn_off=False,
               _training=False):
    # reference: src/operator/nn/dropout.cc
    apply = _training or mode == "always"
    if not apply or p == 0.0:
        return data
    shape = list(data.shape)
    for a in axes:
        shape[a] = 1
    keep = 1.0 - p
    import numpy as _np
    import os as _os

    thresh32 = _np.uint32(min(0xFFFF, int(round(keep * 65536.0))))
    if _os.environ.get("MXNET_TPU_HASH_DROPOUT", "0") == "1" or \
            _os.environ.get("MXNET_PALLAS_FUSED", "0") == "1":
        # MXNET_PALLAS_FUSED also selects the hash path: the fused layer
        # kernels generate THEIR dropout from this same position hash, so
        # one knob keeps every dropout site in the model on one stream
        # family (and the mask fuses into adjacent chains instead of
        # spilling RngBitGenerator bool traffic — the PERF.md batch-32
        # residue bucket the fused kernels target).
        # Stateless position-hash mask (round 5, VERDICT r4 #2 attempt):
        # pure elementwise integer code that XLA fuses into the adjacent
        # chains — zero extra HBM traffic, no RngBitGenerator custom
        # calls. MEASURED SLOWER end-to-end on TPU v5e (BERT-base: 255.6
        # vs 272.6 samples/s): the VPU has no native 32-bit integer
        # multiply, so the 3-multiply murmur finalizer costs more than
        # the hardware RNG kernels it replaces. Kept opt-in for
        # fusion-sensitive CPU paths and as the documented A/B; the flash
        # kernels still use this hash for ATTENTION-prob dropout, where
        # positional statelessness (fwd/bwd mask identity across kernel
        # orientations) has no generator-based alternative.
        from ..pallas_kernels.flash_attention import _hash_u16, fold_key_seed

        seed = fold_key_seed(rng)
        flat = jnp.zeros(tuple(shape), jnp.uint32)
        stride = 1
        for d in reversed(range(len(shape))):
            flat = flat + jax.lax.broadcasted_iota(
                jnp.uint32, tuple(shape), d) * _np.uint32(stride)
            stride *= shape[d]
        mask = _hash_u16(flat, seed) < thresh32
    else:
        # u16 threshold compare instead of jax.random.bernoulli's u32->f32
        # uniform: half the generated bits and no convert, at 2^-16
        # keep-rate granularity. The inverse-keep scale is a multiply
        # (divides don't strength-reduce for non-exact reciprocals).
        bits = jax.random.bits(rng, tuple(shape), dtype=jnp.uint16)
        mask = bits < thresh32.astype(_np.uint16)
    inv_keep = jnp.asarray(1.0 / keep, dtype=data.dtype)
    return jnp.where(mask, data * inv_keep, jnp.zeros_like(data))


# ---------------------------------------------------------------------------
# losses / misc heads
# ---------------------------------------------------------------------------


@register("LinearRegressionOutput")
def linear_regression_output(data, label, *, grad_scale=1.0):
    @jax.custom_vjp
    def _lr(x, y):
        return x

    def fwd(x, y):
        return x, (x, y)

    def bwd(res, g):
        x, y = res
        n = x.shape[0]
        return ((x - y) * grad_scale / 1.0, jnp.zeros_like(y))

    _lr.defvjp(fwd, bwd)
    return _lr(data, label.reshape(data.shape))


@register("MAERegressionOutput")
def mae_regression_output(data, label, *, grad_scale=1.0):
    @jax.custom_vjp
    def _mae(x, y):
        return x

    def fwd(x, y):
        return x, (x, y)

    def bwd(res, g):
        x, y = res
        return (jnp.sign(x - y) * grad_scale, jnp.zeros_like(y))

    _mae.defvjp(fwd, bwd)
    return _mae(data, label.reshape(data.shape))


@register("LogisticRegressionOutput")
def logistic_regression_output(data, label, *, grad_scale=1.0):
    @jax.custom_vjp
    def _log(x, y):
        return jax.nn.sigmoid(x)

    def fwd(x, y):
        out = jax.nn.sigmoid(x)
        return out, (out, y)

    def bwd(res, g):
        out, y = res
        return ((out - y) * grad_scale, jnp.zeros_like(y))

    _log.defvjp(fwd, bwd)
    return _log(data, label.reshape(data.shape))


@register("smooth_l1")
def smooth_l1(data, *, scalar=1.0):
    s2 = scalar * scalar
    return jnp.where(jnp.abs(data) < 1.0 / s2,
                     0.5 * s2 * jnp.square(data),
                     jnp.abs(data) - 0.5 / s2)


@register("CTCLoss", aliases=["ctc_loss"])
def ctc_loss(data, label, data_lengths=None, label_lengths=None, *,
             use_data_lengths=False, use_label_lengths=False, blank_label="first"):
    # reference: src/operator/nn/ctc_loss.cc.  Forward-backward in log space
    # via lax.scan over time — compiler-friendly control flow.
    # data: (seq, batch, alphabet) unnormalized; label: (batch, L) padded with
    # -1 (or 0 when blank_label='last').
    seq_len, batch, alphabet = data.shape
    logprob = jax.nn.log_softmax(data, axis=-1)
    L = label.shape[1]
    blank = 0 if blank_label == "first" else alphabet - 1
    lab = label.astype(jnp.int32)
    if blank_label == "first":
        valid = lab > 0 if not use_label_lengths else (
            jnp.arange(L)[None, :] < label_lengths.astype(jnp.int32)[:, None])
    else:
        valid = lab >= 0 if not use_label_lengths else (
            jnp.arange(L)[None, :] < label_lengths.astype(jnp.int32)[:, None])
    lab_len = jnp.sum(valid.astype(jnp.int32), axis=1)
    # extended label sequence with interleaved blanks: length 2L+1
    S = 2 * L + 1
    ext = jnp.full((batch, S), blank, dtype=jnp.int32)
    ext = ext.at[:, 1::2].set(jnp.where(valid, lab, blank))
    ext_len = 2 * lab_len + 1
    neg_inf = -1e30

    def emit(t):
        # (batch, S) log p of emitting ext symbol at time t
        return jnp.take_along_axis(logprob[t], ext, axis=1)

    alpha0 = jnp.full((batch, S), neg_inf)
    alpha0 = alpha0.at[:, 0].set(logprob[0, :, blank])
    alpha0 = alpha0.at[:, 1].set(jnp.take_along_axis(logprob[0], ext[:, 1:2], axis=1)[:, 0])

    same = jnp.pad(ext[:, 2:] == ext[:, :-2], ((0, 0), (2, 0)), constant_values=True)

    def step(alpha, t):
        a = alpha
        a1 = jnp.pad(alpha[:, :-1], ((0, 0), (1, 0)), constant_values=neg_inf)
        a2 = jnp.pad(alpha[:, :-2], ((0, 0), (2, 0)), constant_values=neg_inf)
        a2 = jnp.where(same, neg_inf, a2)
        new = jnp.logaddexp(jnp.logaddexp(a, a1), a2) + emit(t)
        if use_data_lengths and data_lengths is not None:
            live = (t < data_lengths.astype(jnp.int32))[:, None]
            new = jnp.where(live, new, alpha)
        return new, None

    alphaT, _ = jax.lax.scan(step, alpha0, jnp.arange(1, seq_len))
    idx_last = (ext_len - 1)[:, None]
    last2 = jnp.concatenate([
        jnp.take_along_axis(alphaT, idx_last, axis=1),
        jnp.take_along_axis(alphaT, jnp.maximum(idx_last - 1, 0), axis=1),
    ], axis=1)
    ll = jnp.logaddexp(last2[:, 0], last2[:, 1])
    return -ll


# ---------------------------------------------------------------------------
# upsampling / image-ish nn ops
# ---------------------------------------------------------------------------


@register("UpSampling", variadic=True)
def upsampling(*data, scale=1, sample_type="nearest", num_args=1,
               num_filter=0, multi_input_mode="concat", workspace=512):
    x = data[0]
    if sample_type == "nearest":
        # reference upsampling.cc: EVERY input is upsampled to the common
        # output size data[0].shape * scale — inputs may have different
        # resolutions (FPN-style), each gets its own integer factor
        out_h, out_w = x.shape[2] * scale, x.shape[3] * scale
        ups = [jnp.repeat(jnp.repeat(d, out_h // d.shape[2], axis=2),
                          out_w // d.shape[3], axis=3)
               for d in data]
        if len(ups) == 1:
            return ups[0]
        if multi_input_mode == "sum":
            out = ups[0]
            for u in ups[1:]:
                out = out + u
            return out
        return jnp.concatenate(ups, axis=1)
    if sample_type == "bilinear":
        # reference upsampling.cc: bilinear mode IS a Deconvolution with a
        # caller-supplied (usually bilinear-initialized, learnable) kernel:
        # kernel=2*scale-scale%2, stride=scale, pad=ceil((scale-1)/2)
        if len(data) < 2:
            raise ValueError(
                "UpSampling(sample_type='bilinear') needs a weight input "
                "(reference: upsampling.cc bilinear = Deconvolution)")
        w = data[1]  # (C, 1, k, k): depthwise bilinear kernel, learnable
        k = 2 * scale - scale % 2
        p = scale // 2
        return deconvolution(
            x, w, None, kernel=(k, k), stride=(scale, scale), pad=(p, p),
            num_filter=x.shape[1], num_group=x.shape[1], no_bias=True)
    raise ValueError(f"UpSampling: unknown sample_type {sample_type!r}")


@register("_contrib_BilinearResize2D", aliases=["BilinearResize2D"])
def bilinear_resize_2d(data, *, height=0, width=0, scale_height=None,
                       scale_width=None, mode="size", align_corners=True):
    n, c, h, w = data.shape
    out_h = int(height or round(h * (scale_height or 1)))
    out_w = int(width or round(w * (scale_width or 1)))
    x = jnp.moveaxis(data, 1, -1)
    x = jax.image.resize(x, (n, out_h, out_w, c), method="bilinear")
    return jnp.moveaxis(x, -1, 1)


@register("GridGenerator")
def grid_generator(data, *, transform_type="affine", target_shape=(0, 0)):
    h, w = target_shape
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()], axis=0)  # (3, h*w)
    theta = data.reshape(-1, 2, 3)
    out = jnp.einsum("nij,jk->nik", theta, base)  # (n, 2, h*w)
    return out.reshape(-1, 2, h, w)


@register("mish")
def mish(data):
    # reference: src/operator/nn/activation.cc act_type mish (also reachable
    # via Activation(act_type="mish"))
    return data * jnp.tanh(jax.nn.softplus(data))


@register("im2col", attrs=[
    attr("kernel", tuple, "Sliding window size."),
])
def im2col(data, *, kernel=(), stride=(), dilate=(), pad=()):
    """reference: src/operator/nn/im2col.h — unfold conv patches.

    data (N, C, H, W) -> (N, C*prod(kernel), prod(out_spatial)); the
    gather is conv_general_dilated_patches, which XLA lowers without
    materializing per-tap copies until the consumer needs them.
    """
    nd = len(kernel)
    stride = _tuplize(stride or 1, nd)
    dilate = _tuplize(dilate or 1, nd)
    pad = _tuplize(pad or 0, nd)
    spatial = "DHW"[-nd:]
    lhs = "NC" + spatial
    patches = jax.lax.conv_general_dilated_patches(
        data, tuple(kernel), stride, [(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=(lhs, "OI" + spatial, lhs))
    n = patches.shape[0]
    return patches.reshape(n, patches.shape[1], -1)


@register("col2im", attrs=[
    attr("kernel", tuple, "Sliding window size."),
])
def col2im(data, *, output_size=(), kernel=(), stride=(), dilate=(),
           pad=()):
    """reference: src/operator/nn/im2col.h col2im — scatter-add patches
    back. Implemented as the exact VJP of im2col (the two are adjoint by
    definition), so overlap accumulation is XLA's scatter fusion."""
    nd = len(kernel)
    n, ckk = data.shape[0], data.shape[1]
    c = ckk
    for k in tuple(kernel):
        c //= k
    x_shape = (n, c) + tuple(output_size)
    zero = jnp.zeros(x_shape, dtype=data.dtype)
    _, pull = jax.vjp(
        lambda x: im2col(x, kernel=kernel, stride=stride, dilate=dilate,
                         pad=pad), zero)
    (out,) = pull(data)
    return out


@register("Convolution_v1", aliases=["convolution_v1"])
def convolution_v1(data, weight, bias=None, **kwargs):
    # reference: src/operator/convolution_v1.cc — legacy alias with the
    # modern op's semantics
    return convolution(data, weight, bias, **kwargs)


@register("Pooling_v1", aliases=["pooling_v1"], attrs=[])
def pooling_v1(data, **kwargs):
    return pooling(data, **kwargs)


@register("Crop", eager_only=False)
def crop(*inputs, offset=(0, 0), h_w=(0, 0), center_crop=False,
         num_args=1):
    """reference: src/operator/crop.cc — crop data (NCHW) to h_w or to the
    second input's spatial size."""
    data = inputs[0]
    if len(inputs) > 1:
        th, tw = inputs[1].shape[2], inputs[1].shape[3]
    else:
        th, tw = h_w
        if th <= 0 or tw <= 0:
            raise ValueError(
                "Crop: h_w must be given (positive) when no crop_like "
                "input is passed (reference crop.cc parameter check)")
    h, w = data.shape[2], data.shape[3]
    if center_crop:
        oy, ox = (h - th) // 2, (w - tw) // 2
    else:
        oy, ox = offset
    return data[:, :, oy:oy + th, ox:ox + tw]


@register("softmax_cross_entropy")
def softmax_cross_entropy(data, label):
    # reference: src/operator/loss_binary_op.cc — summed scalar CE over
    # the batch, labels are class indices
    lp = jax.nn.log_softmax(data.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(
        lp, label.astype(jnp.int32).reshape(-1, 1), axis=-1)
    return -jnp.sum(picked)


@_partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _identity_kl_reg(data, sparseness_target, penalty):
    return data


def _identity_kl_fwd(data, sparseness_target, penalty):
    return data, data


def _identity_kl_bwd(sparseness_target, penalty, data, dy):
    rho = sparseness_target
    rho_hat = jnp.clip(jnp.mean(data.astype(jnp.float32), axis=0),
                       1e-6, 1 - 1e-6)
    kl_grad = penalty * (-rho / rho_hat + (1 - rho) / (1 - rho_hat))
    return (dy + kl_grad.astype(dy.dtype),)


_identity_kl_reg.defvjp(_identity_kl_fwd, _identity_kl_bwd)


@register("IdentityAttachKLSparseReg")
def identity_attach_kl_sparse_reg(data, *, sparseness_target=0.1,
                                  penalty=0.001, momentum=0.9):
    """reference: src/operator/identity_attach_KL_sparse_reg.cc —
    identity forward; backward adds the KL sparsity penalty gradient
    computed from the batch mean activation (the reference's moving
    average collapses to the batch mean in a pure-function graph)."""
    return _identity_kl_reg(data, float(sparseness_target), float(penalty))
