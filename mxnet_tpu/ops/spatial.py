"""Spatial sampling + box ops (reference: ``src/operator/bilinear_sampler.cc``,
``spatial_transformer.cc``, ``src/operator/contrib/bounding_box.cc`` ::
``box_nms``/``box_iou``).

All fixed-shape and mask-based (suppressed boxes become -1 rows, never a
dynamic filter) so everything jits onto the TPU — the reference's
CPU/GPU NMS kernels use dynamic output lists, which XLA cannot."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


@register("hard_sigmoid")
def hard_sigmoid(data, *, alpha=0.2, beta=0.5):
    return jnp.clip(alpha * data + beta, 0.0, 1.0)


@register("unravel_index")
def unravel_index(data, *, shape):
    idx = jnp.unravel_index(data.astype(jnp.int64), tuple(shape))
    return jnp.stack(idx, axis=0)


@register("multi_all_finite", variadic=True)
def multi_all_finite(*arrays, num_arrays=1, init_output=True):
    """1 if every element of every input is finite (AMP's global-finite
    check; reference: multi_all_finite.cc). ``init_output`` controls the
    reference's in-place output-buffer reuse; functionally the result is
    always the all-finite predicate of THESE inputs."""
    ok = jnp.bool_(True)
    for a in arrays:
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(
            a.astype(jnp.float32))))
    return ok.astype(jnp.float32).reshape((1,))


def _corner_iou(a, b):
    """Pairwise IoU of corner boxes a (..., M, 4) x b (..., N, 4)."""
    ax1, ay1, ax2, ay2 = [a[..., i] for i in range(4)]
    bx1, by1, bx2, by2 = [b[..., i] for i in range(4)]
    ix1 = jnp.maximum(ax1[..., :, None], bx1[..., None, :])
    iy1 = jnp.maximum(ay1[..., :, None], by1[..., None, :])
    ix2 = jnp.minimum(ax2[..., :, None], bx2[..., None, :])
    iy2 = jnp.minimum(ay2[..., :, None], by2[..., None, :])
    inter = jnp.clip(ix2 - ix1, 0) * jnp.clip(iy2 - iy1, 0)
    area_a = jnp.clip(ax2 - ax1, 0) * jnp.clip(ay2 - ay1, 0)
    area_b = jnp.clip(bx2 - bx1, 0) * jnp.clip(by2 - by1, 0)
    union = area_a[..., :, None] + area_b[..., None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _to_corner(boxes, fmt):
    if fmt == "corner":
        return boxes
    # center: (x, y, w, h) -> corners
    x, y, w, h = [boxes[..., i] for i in range(4)]
    return jnp.stack([x - w / 2, y - h / 2, x + w / 2, y + h / 2], axis=-1)


def _convert_format(boxes, src, dst):
    if src == dst:
        return boxes
    if dst == "corner":
        return _to_corner(boxes, src)
    x1, y1, x2, y2 = [boxes[..., i] for i in range(4)]
    return jnp.stack([(x1 + x2) / 2, (y1 + y2) / 2, x2 - x1, y2 - y1],
                     axis=-1)


@register("_contrib_box_iou", aliases=["box_iou"])
def box_iou(lhs, rhs, *, format="corner"):
    return _corner_iou(_to_corner(lhs.astype(jnp.float32), format),
                       _to_corner(rhs.astype(jnp.float32), format))


@register("_contrib_box_nms", aliases=["box_nms"])
def box_nms(data, *, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1,
            force_suppress=False, in_format="corner",
            out_format="corner"):
    """Greedy per-batch NMS (reference: bounding_box.cc::BoxNMS).

    data: (..., N, K) rows [.., score, .., x1, y1, x2, y2, ..]; returns
    the same shape, score-sorted, suppressed/invalid rows filled -1.
    """
    x = data.astype(jnp.float32)
    batched = x.ndim > 2
    flat = x.reshape((-1,) + x.shape[-2:]) if batched else x[None]

    def one(rows):
        n = rows.shape[0]
        scores = rows[:, score_index]
        order = jnp.argsort(-scores)
        rows = rows[order]
        scores = rows[:, score_index]
        boxes = _to_corner(
            lax.dynamic_slice_in_dim(rows, coord_start, 4, axis=1),
            in_format)
        iou = _corner_iou(boxes, boxes)
        if force_suppress or id_index < 0:
            same_cls = jnp.ones((n, n), bool)
        else:
            ids = rows[:, id_index]
            same_cls = ids[:, None] == ids[None, :]
        valid = scores > valid_thresh
        if topk > 0:
            valid = jnp.logical_and(valid, jnp.arange(n) < topk)

        def step(keep, i):
            kept_i = jnp.logical_and(keep[i], valid[i])
            sup = jnp.logical_and(
                jnp.logical_and(iou[i] > overlap_thresh, same_cls[i]),
                jnp.arange(n) > i)
            keep = jnp.where(jnp.logical_and(kept_i, sup), False, keep)
            return keep, None

        keep, _ = lax.scan(step, jnp.ones(n, bool), jnp.arange(n))
        keep = jnp.logical_and(keep, valid)
        if out_format != in_format:
            # convert kept rows BEFORE masking so -1 sentinels stay -1
            conv = _convert_format(
                rows[:, coord_start:coord_start + 4], in_format, out_format)
            rows = lax.dynamic_update_slice_in_dim(rows, conv, coord_start,
                                                   axis=1)
        return jnp.where(keep[:, None], rows, -jnp.ones_like(rows))

    out = jax.vmap(one)(flat)
    return out.reshape(x.shape) if batched else out[0]


def _bilinear_gather(img, xs, ys):
    """img (C, H, W) sampled at float pixel coords xs/ys (...,) with
    zero padding outside (the reference's border behavior for sampler)."""
    c, h, w = img.shape
    x0 = jnp.floor(xs)
    y0 = jnp.floor(ys)
    dx = xs - x0
    dy = ys - y0

    def at(ix, iy):
        inb = (ix >= 0) & (ix < w) & (iy >= 0) & (iy < h)
        ixc = jnp.clip(ix, 0, w - 1).astype(jnp.int32)
        iyc = jnp.clip(iy, 0, h - 1).astype(jnp.int32)
        vals = img[:, iyc, ixc]                   # (C, ...)
        return jnp.where(inb, vals, 0.0)

    v00 = at(x0, y0)
    v01 = at(x0 + 1, y0)
    v10 = at(x0, y0 + 1)
    v11 = at(x0 + 1, y0 + 1)
    top = v00 * (1 - dx) + v01 * dx
    bot = v10 * (1 - dx) + v11 * dx
    return top * (1 - dy) + bot * dy


@register("BilinearSampler")
def bilinear_sampler(data, grid):
    """data (B, C, H, W); grid (B, 2, Ho, Wo) normalized [-1, 1] (x, y)
    (reference: bilinear_sampler.cc)."""
    data = data.astype(jnp.float32)
    b, c, h, w = data.shape

    def one(img, g):
        xs = (g[0] + 1.0) * (w - 1) / 2.0
        ys = (g[1] + 1.0) * (h - 1) / 2.0
        return _bilinear_gather(img, xs, ys)

    return jax.vmap(one)(data, grid.astype(jnp.float32))


@register("SpatialTransformer")
def spatial_transformer(data, loc, *, target_shape,
                        transform_type="affine",
                        sampler_type="bilinear", cudnn_off=None):
    """Affine spatial transformer network (reference:
    spatial_transformer.cc): loc (B, 6) affine thetas -> sampling grid ->
    bilinear sample."""
    if transform_type != "affine" or sampler_type != "bilinear":
        raise NotImplementedError(
            "SpatialTransformer supports affine + bilinear")
    ho, wo = int(target_shape[0]), int(target_shape[1])
    b = data.shape[0]
    theta = loc.astype(jnp.float32).reshape(b, 2, 3)
    ys, xs = jnp.meshgrid(jnp.linspace(-1.0, 1.0, ho),
                          jnp.linspace(-1.0, 1.0, wo), indexing="ij")
    ones = jnp.ones_like(xs)
    coords = jnp.stack([xs, ys, ones], axis=0).reshape(3, -1)  # (3, Ho*Wo)
    grid = jnp.einsum("bij,jk->bik", theta, coords)            # (B, 2, N)
    grid = grid.reshape(b, 2, ho, wo)
    return bilinear_sampler(data, grid)


@register("ravel_multi_index")
def ravel_multi_index(data, *, shape):
    """Inverse of unravel_index: (ndim, N) indices -> flat (N,)."""
    dims = tuple(int(s) for s in shape)
    idx = [data[i].astype(jnp.int64) for i in range(len(dims))]
    return jnp.ravel_multi_index(idx, dims, mode="clip")


@register("all_finite")
def all_finite(data, *, init_output=True):
    return multi_all_finite(data, num_arrays=1, init_output=init_output)


@register("moments", num_outputs=2)
def moments(data, *, axes=None, keepdims=False):
    """(mean, variance) over ``axes`` (reference: moments.cc)."""
    ax = tuple(axes) if axes is not None else None
    mean = jnp.mean(data, axis=ax, keepdims=True)
    var = jnp.mean((data - mean) ** 2, axis=ax, keepdims=keepdims)
    if not keepdims:
        mean = jnp.squeeze(mean, axis=ax) if ax is not None \
            else jnp.squeeze(mean)
    return mean, var


@register("digamma")
def digamma(data):
    return jax.scipy.special.digamma(data)


def _logical(fn):
    def op(lhs, rhs):
        # result follows the input dtype (reference elemwise logical ops;
        # matches broadcast_logical_* in elemwise.py)
        return fn(lhs.astype(bool), rhs.astype(bool)).astype(
            jnp.result_type(lhs))
    return op


for _n, _f in [("logical_and", jnp.logical_and),
               ("logical_or", jnp.logical_or),
               ("logical_xor", jnp.logical_xor)]:
    register(_n)(_logical(_f))


@register("SoftmaxActivation")
def softmax_activation(data, *, mode="instance"):
    """reference: softmax_activation.cc — softmax over the channel dim
    ('channel' mode) or over all non-batch dims flattened ('instance')."""
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    flat = data.reshape(data.shape[0], -1)
    return jax.nn.softmax(flat, axis=-1).reshape(data.shape)


@register("SVMOutput")
def svm_output(data, label, *, margin=1.0, regularization_coefficient=1.0,
               use_linear=False):
    """reference: svm_output.cc — identity forward (scores), hinge-loss
    backward (L1 with use_linear, else squared hinge), a loss-layer grad
    like SoftmaxOutput's (the incoming cotangent is ignored)."""
    reg = float(regularization_coefficient)
    m = float(margin)

    @jax.custom_vjp
    def _svm(x, lab):
        return x

    def fwd(x, lab):
        return x, (x, lab)

    def bwd(res, g):
        x, lab = res
        li = lab.astype(jnp.int32)
        c = x.shape[-1]
        onehot = jax.nn.one_hot(li, c, dtype=x.dtype)
        score_l = jnp.take_along_axis(x, li[..., None], axis=-1)
        dist = x - score_l + m                      # margin violation
        viol = jnp.logical_and(dist > 0, onehot == 0)
        if use_linear:
            gj = jnp.where(viol, reg, 0.0)
        else:
            gj = jnp.where(viol, 2.0 * reg * dist, 0.0)
        grad = gj - onehot * jnp.sum(gj, axis=-1, keepdims=True)
        return grad.astype(x.dtype), jnp.zeros_like(lab)

    _svm.defvjp(fwd, bwd)
    return _svm(data, label)
