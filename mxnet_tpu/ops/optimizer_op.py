"""Optimizer update operators.

Reference: ``src/operator/optimizer_op.cc`` — `sgd_update`, `sgd_mom_update`,
`adam_update`, `nag_mom_update`, `rmsprop_update`, `rmspropalex_update`,
`ftrl_update`, `signsgd_update`, `signum_update`, `lamb_update_phase1/2`,
multi-precision (`mp_*`) and multi-tensor (`multi_sgd_*`) variants;
``src/operator/contrib/adamw.cc`` for AdamW.

These are pure functions returning the updated tensors; the imperative
wrapper writes results back through the ``out=`` mechanism, giving MXNet's
in-place update semantics, while hybridized/Module training fuses them into
the jitted step (the SURVEY.md §3.5 "whole step is ONE executable" design).
All state math runs in fp32 even for fp16/bf16 weights when the `mp_`
variants are used, matching MXNet's multi-precision contract.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register


def _apply_wd(grad, weight, wd, rescale_grad, clip_gradient):
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g + wd * weight.astype(jnp.float32)


@register("sgd_update")
def sgd_update(weight, grad, *, lr, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=True):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient)
    return (weight.astype(jnp.float32) - lr * g).astype(weight.dtype)


@register("sgd_mom_update")
def sgd_mom_update(weight, grad, mom, *, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient)
    new_mom = momentum * mom.astype(jnp.float32) - lr * g
    new_w = weight.astype(jnp.float32) + new_mom
    return new_w.astype(weight.dtype), new_mom.astype(mom.dtype)


@register("mp_sgd_update")
def mp_sgd_update(weight, grad, weight32, *, lr, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, lazy_update=True):
    g = _apply_wd(grad, weight32, wd, rescale_grad, clip_gradient)
    new_w32 = weight32 - lr * g
    return new_w32.astype(weight.dtype), new_w32


@register("mp_sgd_mom_update")
def mp_sgd_mom_update(weight, grad, mom, weight32, *, lr, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                      lazy_update=True):
    g = _apply_wd(grad, weight32, wd, rescale_grad, clip_gradient)
    new_mom = momentum * mom - lr * g
    new_w32 = weight32 + new_mom
    return new_w32.astype(weight.dtype), new_mom, new_w32


@register("nag_mom_update")
def nag_mom_update(weight, grad, mom, *, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient)
    new_mom = momentum * mom.astype(jnp.float32) + g
    new_w = weight.astype(jnp.float32) - lr * (g + momentum * new_mom)
    return new_w.astype(weight.dtype), new_mom.astype(mom.dtype)


@register("adam_update")
def adam_update(weight, grad, mean, var, *, lr, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient)
    new_mean = beta1 * mean.astype(jnp.float32) + (1 - beta1) * g
    new_var = beta2 * var.astype(jnp.float32) + (1 - beta2) * jnp.square(g)
    new_w = weight.astype(jnp.float32) - lr * new_mean / (jnp.sqrt(new_var) + epsilon)
    return new_w.astype(weight.dtype), new_mean.astype(mean.dtype), new_var.astype(var.dtype)


@register("_contrib_adamw_update", aliases=["adamw_update"])
def adamw_update(weight, grad, mean, var, rescale_grad_t=None, *, lr, beta1=0.9,
                 beta2=0.999, epsilon=1e-8, wd=0.0, eta=1.0,
                 clip_gradient=-1.0, rescale_grad=1.0):
    # reference: src/operator/contrib/adamw.cc — decoupled weight decay;
    # rescale_grad may arrive as a tensor (NaN-check for AMP loss scaling).
    rs = rescale_grad_t if rescale_grad_t is not None else rescale_grad
    g = grad.astype(jnp.float32) * rs
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    w32 = weight.astype(jnp.float32)
    new_w = w32 - eta * (lr * new_mean / (jnp.sqrt(new_var) + epsilon) + wd * lr * w32)
    # skip update if grads were non-finite (AMP overflow step)
    ok = jnp.isfinite(g).all()
    new_w = jnp.where(ok, new_w, w32)
    new_mean = jnp.where(ok, new_mean, mean)
    new_var = jnp.where(ok, new_var, var)
    return new_w.astype(weight.dtype), new_mean, new_var


@register("rmsprop_update")
def rmsprop_update(weight, grad, n, *, lr, gamma1=0.95, epsilon=1e-8, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, clip_weights=-1.0):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient)
    new_n = gamma1 * n + (1 - gamma1) * jnp.square(g)
    new_w = weight.astype(jnp.float32) - lr * g / jnp.sqrt(new_n + epsilon)
    if clip_weights is not None and clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w.astype(weight.dtype), new_n


@register("rmspropalex_update")
def rmspropalex_update(weight, grad, n, g_acc, delta, *, lr, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient)
    new_n = gamma1 * n + (1 - gamma1) * jnp.square(g)
    new_g = gamma1 * g_acc + (1 - gamma1) * g
    new_delta = gamma2 * delta - lr * g / jnp.sqrt(new_n - jnp.square(new_g) + epsilon)
    new_w = weight.astype(jnp.float32) + new_delta
    if clip_weights is not None and clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w.astype(weight.dtype), new_n, new_g, new_delta


@register("ftrl_update")
def ftrl_update(weight, grad, z, n, *, lr, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0):
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight.astype(jnp.float32)
    new_w = jnp.where(
        jnp.abs(new_z) <= lamda1,
        jnp.zeros_like(new_z),
        -(new_z - jnp.sign(new_z) * lamda1) / ((beta + jnp.sqrt(new_n)) / lr + wd),
    )
    return new_w.astype(weight.dtype), new_z, new_n


@register("signsgd_update")
def signsgd_update(weight, grad, *, lr, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_w = (1 - lr * wd) * weight.astype(jnp.float32) - lr * jnp.sign(g)
    return new_w.astype(weight.dtype)


@register("signum_update")
def signum_update(weight, grad, mom, *, lr, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mom = momentum * mom - (1 - momentum) * (g + wd * weight.astype(jnp.float32))
    new_w = (1 - lr * wd_lh) * weight.astype(jnp.float32) + lr * jnp.sign(new_mom)
    return new_w.astype(weight.dtype), new_mom


@register("adagrad_update", aliases=["_sparse_adagrad_update"])
def adagrad_update(weight, grad, history, *, lr, epsilon=1e-7, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient)
    new_h = history + jnp.square(g)
    new_w = weight.astype(jnp.float32) - lr * g / (jnp.sqrt(new_h) + epsilon)
    return new_w.astype(weight.dtype), new_h


@register("adadelta_update")
def adadelta_update(weight, grad, acc_g, acc_delta, *, rho=0.9, epsilon=1e-5,
                    wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient)
    new_acc_g = rho * acc_g + (1 - rho) * jnp.square(g)
    delta = jnp.sqrt(acc_delta + epsilon) / jnp.sqrt(new_acc_g + epsilon) * g
    new_acc_delta = rho * acc_delta + (1 - rho) * jnp.square(delta)
    new_w = weight.astype(jnp.float32) - delta
    return new_w.astype(weight.dtype), new_acc_g, new_acc_delta


@register("lamb_update_phase1")
def lamb_update_phase1(weight, grad, mean, var, *, beta1=0.9, beta2=0.999,
                       epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0):
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    m, v = new_mean, new_var
    if bias_correction:
        m = m / (1 - beta1 ** t)
        v = v / (1 - beta2 ** t)
    update = m / (jnp.sqrt(v) + epsilon) + wd * weight.astype(jnp.float32)
    return update, new_mean, new_var


# ---------------------------------------------------------------------------
# Multi-tensor updates (reference: optimizer_op.cc `multi_sgd_update`,
# `multi_sgd_mom_update`, `multi_mp_sgd_*`, `preloaded_multi_*`,
# `multi_sum_sq`). Upstream fuses one kernel launch over a whole parameter
# list and mutates momenta in place via mutable inputs; the functional
# equivalent returns every updated tensor, interleaved per weight in input
# order (same convention as the single-tensor ops above, which return
# updated state as extra outputs).
#
# Since the fused-sweep engine landed, these ops are RE-EXPRESSED on its
# packed layout (``optimizer/multi_tensor.py::packed_apply``): members of
# like dtype are coalesced into flat buffers and the whole group updates
# in one elementwise sweep (the Pallas kernel on TPU under
# MXNET_PALLAS_FUSED, the identical jnp math otherwise) — the upstream
# op's one-kernel-per-list behavior, not just something XLA may or may
# not fuse back together.
# ---------------------------------------------------------------------------


def _per_weight(v, i):
    """lrs/wds arrive as a python tuple (attr) or a 1-D tensor (preloaded)."""
    if isinstance(v, (tuple, list)):
        return v[i]
    if hasattr(v, "shape") and getattr(v, "ndim", 0) >= 1:
        return v[i]
    return v


def _packed_groups(ws, gs, mp):
    """Member-index groups from the sweep engine's ONE bucket planner
    (dtype rule + the ``MXNET_OPT_BUCKET_MB`` size-class cap) —
    re-deriving the grouping here would fork the contract."""
    from ..optimizer import multi_tensor as mt

    entries = [(tuple(w.shape), str(w.dtype), str(g.dtype))
               for w, g in zip(ws, gs)]
    return [list(b.members)
            for b in mt.plan_buckets(entries, multi_precision=mp)]


def _packed_multi_sgd(ws, gs, moms, w32s, lrs, wds, momentum,
                      rescale_grad, clip_gradient):
    """The packed SGD family sweep behind every ``multi_*sgd*`` op.

    Returns per-member role dict lists (w/[mom]/[w32]) in input order.
    """
    from ..optimizer import multi_tensor as mt

    n = len(ws)
    static = {"momentum": float(momentum), "clip_gradient": clip_gradient}
    out_w = [None] * n
    out_m = [None] * n if moms is not None else None
    out_w32 = [None] * n if w32s is not None else None
    for idxs in _packed_groups(ws, gs, w32s is not None):
        shapes = [tuple(ws[i].shape) for i in idxs]
        ins = {"g": [gs[i] for i in idxs]}
        if w32s is not None:
            ins["w"] = [w32s[i] for i in idxs]
            low_dtype = ws[idxs[0]].dtype
        else:
            ins["w"] = [ws[i] for i in idxs]
            low_dtype = None
        if moms is not None:
            ins["mom"] = [moms[i] for i in idxs]
        vecs = {"lr": [_per_weight(lrs, i) for i in idxs],
                "wd": [_per_weight(wds, i) for i in idxs]}
        new = mt.packed_apply("sgd", static, shapes, ins, vecs,
                              rescale_grad, low_dtype=low_dtype)
        for j, i in enumerate(idxs):
            out_w[i] = new["w_low"][j] if w32s is not None else new["w"][j]
            if out_m is not None:
                out_m[i] = new["mom"][j]
            if out_w32 is not None:
                out_w32[i] = new["w"][j]
    return out_w, out_m, out_w32


@register("multi_sgd_update", variadic=True)
def multi_sgd_update(*inputs, lrs, wds, rescale_grad=1.0, clip_gradient=-1.0,
                     num_weights=None):
    """Fused SGD over a parameter list. Inputs: w0, g0, w1, g1, ...;
    outputs: updated weights in order."""
    n = num_weights if num_weights is not None else len(inputs) // 2
    ws = [inputs[2 * i] for i in range(n)]
    gs = [inputs[2 * i + 1] for i in range(n)]
    out_w, _, _ = _packed_multi_sgd(ws, gs, None, None, lrs, wds, 0.0,
                                    rescale_grad, clip_gradient)
    return tuple(out_w)


@register("multi_sgd_mom_update", variadic=True)
def multi_sgd_mom_update(*inputs, lrs, wds, momentum=0.0, rescale_grad=1.0,
                         clip_gradient=-1.0, num_weights=None):
    """Inputs: w0, g0, m0, w1, g1, m1, ...; outputs: w0', m0', w1', m1', ..."""
    n = num_weights if num_weights is not None else len(inputs) // 3
    ws = [inputs[3 * i] for i in range(n)]
    gs = [inputs[3 * i + 1] for i in range(n)]
    ms = [inputs[3 * i + 2] for i in range(n)]
    out_w, out_m, _ = _packed_multi_sgd(ws, gs, ms, None, lrs, wds,
                                        momentum, rescale_grad,
                                        clip_gradient)
    outs = []
    for i in range(n):
        outs.extend((out_w[i], out_m[i]))
    return tuple(outs)


@register("multi_mp_sgd_update", variadic=True)
def multi_mp_sgd_update(*inputs, lrs, wds, rescale_grad=1.0,
                        clip_gradient=-1.0, num_weights=None):
    """Inputs: w0, g0, w32_0, ...; outputs: w0', w32_0', ..."""
    n = num_weights if num_weights is not None else len(inputs) // 3
    ws = [inputs[3 * i] for i in range(n)]
    gs = [inputs[3 * i + 1] for i in range(n)]
    w32s = [inputs[3 * i + 2] for i in range(n)]
    out_w, _, out_w32 = _packed_multi_sgd(ws, gs, None, w32s, lrs, wds,
                                          0.0, rescale_grad,
                                          clip_gradient)
    outs = []
    for i in range(n):
        outs.extend((out_w[i], out_w32[i]))
    return tuple(outs)


@register("multi_mp_sgd_mom_update", variadic=True)
def multi_mp_sgd_mom_update(*inputs, lrs, wds, momentum=0.0, rescale_grad=1.0,
                            clip_gradient=-1.0, num_weights=None):
    """Inputs: w0, g0, m0, w32_0, ...; outputs: w0', m0', w32_0', ..."""
    n = num_weights if num_weights is not None else len(inputs) // 4
    ws = [inputs[4 * i] for i in range(n)]
    gs = [inputs[4 * i + 1] for i in range(n)]
    ms = [inputs[4 * i + 2] for i in range(n)]
    w32s = [inputs[4 * i + 3] for i in range(n)]
    out_w, out_m, out_w32 = _packed_multi_sgd(ws, gs, ms, w32s, lrs, wds,
                                              momentum, rescale_grad,
                                              clip_gradient)
    outs = []
    for i in range(n):
        outs.extend((out_w[i], out_m[i], out_w32[i]))
    return tuple(outs)


def _packed_multi_lamb(ws, gs, ms, vs, w32s, lrs, wds, beta1, beta2,
                       epsilon, t, bias_correction, lower_bound,
                       upper_bound, rescale_grad, clip_gradient):
    from ..optimizer import multi_tensor as mt

    n = len(ws)
    static = {"beta1": beta1, "beta2": beta2, "epsilon": epsilon,
              "bias_correction": bool(bias_correction),
              "lower_bound": lower_bound, "upper_bound": upper_bound,
              "clip_gradient": clip_gradient, "bc_recip": False}
    out = {"w": [None] * n, "mean": [None] * n, "var": [None] * n,
           "w32": [None] * n if w32s is not None else None}
    for idxs in _packed_groups(ws, gs, w32s is not None):
        shapes = [tuple(ws[i].shape) for i in idxs]
        ins = {"g": [gs[i] for i in idxs],
               "mean": [ms[i] for i in idxs],
               "var": [vs[i] for i in idxs]}
        if w32s is not None:
            ins["w"] = [w32s[i] for i in idxs]
            low_dtype = ws[idxs[0]].dtype
        else:
            ins["w"] = [ws[i] for i in idxs]
            low_dtype = None
        vecs = {"lr": [_per_weight(lrs, i) for i in idxs],
                "wd": [_per_weight(wds, i) for i in idxs]}
        if bias_correction:
            vecs["bc1"] = [1.0 - beta1 ** t] * len(idxs)
            vecs["bc2"] = [1.0 - beta2 ** t] * len(idxs)
        new = mt.packed_apply("lamb", static, shapes, ins, vecs,
                              rescale_grad, low_dtype=low_dtype)
        for j, i in enumerate(idxs):
            out["w"][i] = new["w_low"][j] if w32s is not None \
                else new["w"][j]
            out["mean"][i] = new["mean"][j]
            out["var"][i] = new["var"][j]
            if out["w32"] is not None:
                out["w32"][i] = new["w"][j]
    return out


@register("multi_lamb_update", variadic=True)
def multi_lamb_update(*inputs, lrs, wds, beta1=0.9, beta2=0.999,
                      epsilon=1e-6, t=1, bias_correction=True,
                      rescale_grad=1.0, clip_gradient=-1.0,
                      lower_bound=-1.0, upper_bound=-1.0,
                      num_weights=None):
    """Horizontally-fused LAMB over a parameter list (reference:
    mp_lamb_update_phase1/2 looped per weight). Inputs: w0, g0, m0, v0,
    ...; outputs: w0', m0', v0', ... Both elementwise phases run on the
    packed dtype buckets; the per-tensor trust-ratio norms run as one
    ``multi_sum_sq``-style pass over the packed buffer."""
    n = num_weights if num_weights is not None else len(inputs) // 4
    ws = [inputs[4 * i] for i in range(n)]
    gs = [inputs[4 * i + 1] for i in range(n)]
    ms = [inputs[4 * i + 2] for i in range(n)]
    vs = [inputs[4 * i + 3] for i in range(n)]
    out = _packed_multi_lamb(ws, gs, ms, vs, None, lrs, wds, beta1,
                             beta2, epsilon, t, bias_correction,
                             lower_bound, upper_bound, rescale_grad,
                             clip_gradient)
    outs = []
    for i in range(n):
        outs.extend((out["w"][i], out["mean"][i], out["var"][i]))
    return tuple(outs)


@register("multi_mp_lamb_update", variadic=True)
def multi_mp_lamb_update(*inputs, lrs, wds, beta1=0.9, beta2=0.999,
                         epsilon=1e-6, t=1, bias_correction=True,
                         rescale_grad=1.0, clip_gradient=-1.0,
                         lower_bound=-1.0, upper_bound=-1.0,
                         num_weights=None):
    """Multi-precision fused LAMB. Inputs: w0, g0, m0, v0, w32_0, ...;
    outputs: w0', m0', v0', w32_0', ... — the mp_lamb_update_phase1/2
    pair horizontally fused across the list on the packed layout."""
    n = num_weights if num_weights is not None else len(inputs) // 5
    ws = [inputs[5 * i] for i in range(n)]
    gs = [inputs[5 * i + 1] for i in range(n)]
    ms = [inputs[5 * i + 2] for i in range(n)]
    vs = [inputs[5 * i + 3] for i in range(n)]
    w32s = [inputs[5 * i + 4] for i in range(n)]
    out = _packed_multi_lamb(ws, gs, ms, vs, w32s, lrs, wds, beta1,
                             beta2, epsilon, t, bias_correction,
                             lower_bound, upper_bound, rescale_grad,
                             clip_gradient)
    outs = []
    for i in range(n):
        outs.extend((out["w"][i], out["mean"][i], out["var"][i],
                     out["w32"][i]))
    return tuple(outs)


@register("preloaded_multi_sgd_update", variadic=True)
def preloaded_multi_sgd_update(*inputs, rescale_grad=1.0, clip_gradient=-1.0,
                               num_weights=None):
    """`multi_sgd_update` with lrs/wds as trailing 1-D tensor inputs
    (reference: preloaded_multi_sgd_update — keeps the schedule on-device)."""
    lrs, wds = inputs[-2], inputs[-1]
    return multi_sgd_update(*inputs[:-2], lrs=lrs, wds=wds,
                            rescale_grad=rescale_grad,
                            clip_gradient=clip_gradient,
                            num_weights=num_weights)


@register("preloaded_multi_sgd_mom_update", variadic=True)
def preloaded_multi_sgd_mom_update(*inputs, momentum=0.0, rescale_grad=1.0,
                                   clip_gradient=-1.0, num_weights=None):
    lrs, wds = inputs[-2], inputs[-1]
    return multi_sgd_mom_update(*inputs[:-2], lrs=lrs, wds=wds,
                                momentum=momentum, rescale_grad=rescale_grad,
                                clip_gradient=clip_gradient,
                                num_weights=num_weights)


@register("preloaded_multi_mp_sgd_update", variadic=True)
def preloaded_multi_mp_sgd_update(*inputs, rescale_grad=1.0,
                                  clip_gradient=-1.0, num_weights=None):
    lrs, wds = inputs[-2], inputs[-1]
    return multi_mp_sgd_update(*inputs[:-2], lrs=lrs, wds=wds,
                               rescale_grad=rescale_grad,
                               clip_gradient=clip_gradient,
                               num_weights=num_weights)


@register("preloaded_multi_mp_sgd_mom_update", variadic=True)
def preloaded_multi_mp_sgd_mom_update(*inputs, momentum=0.0, rescale_grad=1.0,
                                      clip_gradient=-1.0, num_weights=None):
    lrs, wds = inputs[-2], inputs[-1]
    return multi_mp_sgd_mom_update(*inputs[:-2], lrs=lrs, wds=wds,
                                   momentum=momentum,
                                   rescale_grad=rescale_grad,
                                   clip_gradient=clip_gradient,
                                   num_weights=num_weights)


@register("multi_sum_sq", variadic=True)
def multi_sum_sq(*inputs, num_arrays=None):
    """Per-tensor sum of squares, stacked into one 1-D result (reference:
    multi_sum_sq — the LARS trust-ratio building block)."""
    n = num_arrays if num_arrays is not None else len(inputs)
    return jnp.stack([jnp.sum(jnp.square(x.astype(jnp.float32)))
                      for x in inputs[:n]])


@register("lamb_update_phase2")
def lamb_update_phase2(weight, g_update, r1, r2, *, lr, lower_bound=-1.0,
                       upper_bound=-1.0):
    r1v = r1.reshape(())
    r2v = r2.reshape(())
    if lower_bound is not None and lower_bound >= 0:
        r1v = jnp.maximum(r1v, lower_bound)
    if upper_bound is not None and upper_bound >= 0:
        r1v = jnp.minimum(r1v, upper_bound)
    ratio = jnp.where((r1v > 0) & (r2v > 0), r1v / r2v, 1.0)
    new_w = weight.astype(jnp.float32) - lr * ratio * g_update
    return new_w.astype(weight.dtype)


@register("ftml_update")
def ftml_update(weight, grad, d, v, z, *, lr, beta1=0.6, beta2=0.999,
                epsilon=1e-8, t=1, wd=0.0, rescale_grad=1.0,
                clip_grad=-1.0):
    """FTML (Follow The Moving Leader; reference optimizer_op.cc
    ftml_update, states d/v/z)."""
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_grad is not None and clip_grad >= 0:
        g = jnp.clip(g, -clip_grad, clip_grad)
    g = g + wd * weight.astype(jnp.float32)
    new_v = beta2 * v + (1 - beta2) * jnp.square(g)
    d_t = (1 - beta1 ** t) / lr * (
        jnp.sqrt(new_v / (1 - beta2 ** t)) + epsilon)
    sigma = d_t - beta1 * d
    new_z = beta1 * z + (1 - beta1) * g - sigma * weight.astype(jnp.float32)
    new_w = -new_z / d_t
    return (new_w.astype(weight.dtype), d_t.astype(d.dtype),
            new_v.astype(v.dtype), new_z.astype(z.dtype))


@register("mp_nag_mom_update")
def mp_nag_mom_update(weight, grad, mom, weight32, *, lr, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _apply_wd(grad, weight32, wd, rescale_grad, clip_gradient)
    new_mom = momentum * mom + g
    new_w32 = weight32 - lr * (g + momentum * new_mom)
    return new_w32.astype(weight.dtype), new_mom, new_w32


@register("mp_lamb_update_phase1")
def mp_lamb_update_phase1(weight, grad, mean, var, weight32, *, beta1=0.9,
                          beta2=0.999, epsilon=1e-6, t=1,
                          bias_correction=True, wd=0.0, rescale_grad=1.0,
                          clip_gradient=-1.0):
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    m, v = new_mean, new_var
    if bias_correction:
        m = m / (1 - beta1 ** t)
        v = v / (1 - beta2 ** t)
    update = m / (jnp.sqrt(v) + epsilon) + wd * weight32
    return update, new_mean, new_var


@register("mp_lamb_update_phase2")
def mp_lamb_update_phase2(weight, g_update, r1, r2, weight32, *, lr,
                          lower_bound=-1.0, upper_bound=-1.0):
    r1v = r1.reshape(())
    r2v = r2.reshape(())
    if lower_bound is not None and lower_bound >= 0:
        r1v = jnp.maximum(r1v, lower_bound)
    if upper_bound is not None and upper_bound >= 0:
        r1v = jnp.minimum(r1v, upper_bound)
    ratio = jnp.where((r1v > 0) & (r2v > 0), r1v / r2v, 1.0)
    new_w32 = weight32 - lr * ratio * g_update
    return new_w32.astype(weight.dtype), new_w32
