"""SSD multibox ops (reference: ``src/operator/contrib/multibox_prior.cc``,
``multibox_target.cc``, ``multibox_detection.cc`` — the op trio behind the
reference's SSD example and GluonCV's SSD family).

All mask-based fixed shapes (XLA-friendly): targets use argmax bipartite
matching + threshold matching like the reference; detection decodes
center-variance boxes then routes through the jit-friendly box_nms.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register
from .spatial import box_nms as _box_nms


@register("_contrib_MultiBoxPrior", aliases=["MultiBoxPrior"])
def multibox_prior(data, *, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """Anchor boxes for one feature map (reference: multibox_prior.cc).

    data: (B, C, H, W). Returns (1, H*W*A, 4) corner boxes in [0, 1]
    units with A = len(sizes) + len(ratios) - 1 (first size pairs with
    every ratio; remaining sizes use ratio 1 — the reference's layout).
    """
    h, w = data.shape[2], data.shape[3]
    sizes = tuple(float(s) for s in sizes)
    ratios = tuple(float(r) for r in ratios)
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w
    cy = (jnp.arange(h, dtype=jnp.float32) + offsets[0]) * step_y
    cx = (jnp.arange(w, dtype=jnp.float32) + offsets[1]) * step_x
    cyx = jnp.stack(jnp.meshgrid(cy, cx, indexing="ij"), axis=-1)  # (H,W,2)

    # reference emission order (multibox_prior.cc): every size with the
    # FIRST ratio, then the first size with each remaining ratio
    wh = []
    r0 = float(ratios[0]) ** 0.5
    for s in sizes:
        wh.append((s * r0, s / r0))
    for r in ratios[1:]:
        sr = float(r) ** 0.5
        wh.append((sizes[0] * sr, sizes[0] / sr))
    wh = jnp.asarray(wh, jnp.float32)                    # (A, 2) = (w, h)

    a = wh.shape[0]
    centers = jnp.broadcast_to(cyx[:, :, None, :], (h, w, a, 2))
    half_w = wh[None, None, :, 0] / 2
    half_h = wh[None, None, :, 1] / 2
    boxes = jnp.stack([
        centers[..., 1] - half_w, centers[..., 0] - half_h,
        centers[..., 1] + half_w, centers[..., 0] + half_h], axis=-1)
    boxes = boxes.reshape(1, h * w * a, 4)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes


def _corners_to_center(b):
    return jnp.stack([(b[..., 0] + b[..., 2]) / 2,
                      (b[..., 1] + b[..., 3]) / 2,
                      jnp.clip(b[..., 2] - b[..., 0], 1e-12),
                      jnp.clip(b[..., 3] - b[..., 1], 1e-12)], axis=-1)


from .spatial import _corner_iou as _iou_corner  # noqa: E402  (shared math)


@register("_contrib_MultiBoxTarget", aliases=["MultiBoxTarget"],
          num_outputs=3)
def multibox_target(anchor, label, cls_pred, *, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """Training targets (reference: multibox_target.cc).

    anchor (1, N, 4) corners; label (B, M, 5) [cls, x1, y1, x2, y2] with
    -1 padding; cls_pred (B, num_cls+1, N) (used for hard negative
    mining when negative_mining_ratio > 0). Returns
    (loc_target (B, N*4), loc_mask (B, N*4), cls_target (B, N)) with
    cls_target 0 = background, k+1 = object class k.
    """
    anchors = anchor.reshape(-1, 4).astype(jnp.float32)  # (N, 4)
    n = anchors.shape[0]
    var = jnp.asarray(variances, jnp.float32)
    a_ctr = _corners_to_center(anchors)

    def one(lab, cp):
        valid = lab[:, 0] >= 0                            # (M,)
        gt = lab[:, 1:5]
        iou = _iou_corner(anchors, gt)                    # (N, M)
        iou = jnp.where(valid[None, :], iou, -1.0)
        best_gt = jnp.argmax(iou, axis=1)                 # (N,)
        best_iou = jnp.max(iou, axis=1)
        # iterative bipartite matching (reference multibox_target.cc):
        # repeatedly claim the GLOBAL best (anchor, gt) pair and retire
        # both, so gts sharing a best anchor each still get one — a
        # single-shot argmax scatter would drop the loser
        m_gt = lab.shape[0]

        def bi_step(carry, _):
            iou_c, claim = carry
            flat = jnp.argmax(iou_c)
            ai = (flat // m_gt).astype(jnp.int32)
            gj = (flat % m_gt).astype(jnp.int32)
            ok = iou_c[ai, gj] > 0
            claim = claim.at[ai].set(jnp.where(ok, gj, claim[ai]))
            iou_c = jnp.where(ok, iou_c.at[ai, :].set(-jnp.inf), iou_c)
            iou_c = jnp.where(ok, iou_c.at[:, gj].set(-jnp.inf), iou_c)
            return (iou_c, claim), None

        masked = jnp.where(valid[None, :], iou, -jnp.inf)
        (_, claim), _ = jax.lax.scan(
            bi_step, (masked, jnp.full(n, -1, jnp.int32)), None,
            length=m_gt)
        forced = claim >= 0
        matched = jnp.logical_or(forced, best_iou >= overlap_threshold)
        gt_idx = jnp.where(forced, claim, best_gt)
        g = gt[gt_idx]                                    # (N, 4)
        g_ctr = _corners_to_center(g)
        loc_t = jnp.stack([
            (g_ctr[:, 0] - a_ctr[:, 0]) / a_ctr[:, 2] / var[0],
            (g_ctr[:, 1] - a_ctr[:, 1]) / a_ctr[:, 3] / var[1],
            jnp.log(g_ctr[:, 2] / a_ctr[:, 2]) / var[2],
            jnp.log(g_ctr[:, 3] / a_ctr[:, 3]) / var[3]], axis=-1)
        loc_t = jnp.where(matched[:, None], loc_t, 0.0).reshape(-1)
        loc_m = jnp.where(matched[:, None],
                          jnp.ones((n, 4), jnp.float32), 0.0).reshape(-1)
        cls_t = jnp.where(matched, lab[gt_idx, 0] + 1.0, 0.0)
        if negative_mining_ratio > 0:
            # hard negatives: keep the top-k background anchors by
            # background NEGATIVE-confidence (1 - p_bg proxy via max
            # non-bg logit), others -> ignore_label. Near-positives
            # (IoU >= negative_mining_thresh but below the match
            # threshold) are excluded from mining — the reference
            # ignores them rather than training them as background
            bg_score = cp[0]                              # (N,)
            excluded = jnp.logical_or(
                matched, best_iou >= negative_mining_thresh)
            hardness = jnp.where(excluded, -jnp.inf, -bg_score)
            k = jnp.maximum(
                (matched.sum() * negative_mining_ratio).astype(jnp.int32),
                jnp.int32(minimum_negative_samples))
            order = jnp.argsort(-hardness)
            rank = jnp.zeros(n, jnp.int32).at[order].set(
                jnp.arange(n, dtype=jnp.int32))
            keep_neg = jnp.logical_and(~matched, rank < k)
            cls_t = jnp.where(jnp.logical_or(matched, keep_neg), cls_t,
                              jnp.float32(ignore_label))
        return loc_t, loc_m, cls_t

    return jax.vmap(one)(label.astype(jnp.float32),
                         cls_pred.astype(jnp.float32))


@register("_contrib_MultiBoxDetection", aliases=["MultiBoxDetection"])
def multibox_detection(cls_prob, loc_pred, anchor, *, clip=True,
                       threshold=0.01, background_id=0, nms_threshold=0.5,
                       force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """Decode + NMS (reference: multibox_detection.cc).

    cls_prob (B, num_cls+1, N); loc_pred (B, N*4); anchor (1, N, 4).
    Returns (B, N, 6) rows [cls_id, score, x1, y1, x2, y2], -1-filled for
    suppressed/background.
    """
    anchors = anchor.reshape(-1, 4).astype(jnp.float32)
    a_ctr = _corners_to_center(anchors)
    var = jnp.asarray(variances, jnp.float32)

    def one(cp, lp):
        n = anchors.shape[0]
        delta = lp.reshape(n, 4)
        cx = a_ctr[:, 0] + delta[:, 0] * var[0] * a_ctr[:, 2]
        cy = a_ctr[:, 1] + delta[:, 1] * var[1] * a_ctr[:, 3]
        bw = a_ctr[:, 2] * jnp.exp(delta[:, 2] * var[2])
        bh = a_ctr[:, 3] * jnp.exp(delta[:, 3] * var[3])
        boxes = jnp.stack([cx - bw / 2, cy - bh / 2,
                           cx + bw / 2, cy + bh / 2], axis=-1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        # best non-background class per anchor (the reference's layout)
        fg = jnp.delete(cp, background_id, axis=0,
                        assume_unique_indices=True)        # (C, N)
        cls_id = jnp.argmax(fg, axis=0).astype(jnp.float32)
        score = jnp.max(fg, axis=0)
        keep = score > threshold
        rows = jnp.concatenate([
            jnp.where(keep, cls_id, -1.0)[:, None],
            jnp.where(keep, score, -1.0)[:, None], boxes], axis=-1)
        return _box_nms(rows, overlap_thresh=nms_threshold,
                        valid_thresh=max(threshold, 0.0), topk=nms_topk,
                        coord_start=2, score_index=1, id_index=0,
                        force_suppress=force_suppress)

    return jax.vmap(one)(cls_prob.astype(jnp.float32),
                         loc_pred.astype(jnp.float32))
