"""Deformable / correlation / position-sensitive spatial operators.

Reference: ``src/operator/contrib/deformable_convolution.cc`` (Deformable
ConvNets), ``src/operator/correlation.cc`` (FlowNet cost volume),
``src/operator/contrib/psroi_pooling.cc`` (R-FCN position-sensitive ROI
pooling). The CUDA implementations are hand-written gather kernels; the
TPU-native re-design expresses each as dense, statically-shaped tensor
algebra — bilinear sampling becomes four clipped gathers that XLA
vectorizes, the deformable im2col becomes a (B, K*K, C, H, W) sampled
volume contracted on the MXU, and the correlation window becomes a
shifted-product reduction — so every op jits, differentiates through AD,
and shards under GSPMD without custom backward code.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register
from .spatial import _bilinear_gather as _bilinear_xy


def _bilinear_gather(img, y, x):
    """(y, x)-ordered wrapper over the shared zero-padded bilinear
    gather in ops/spatial.py (one border/dtype policy for
    BilinearSampler, SpatialTransformer and the deformable family)."""
    return _bilinear_xy(img, x, y)


@register("_contrib_DeformableConvolution",
          aliases=["DeformableConvolution"])
def deformable_convolution(data, offset, weight, bias=None, *, kernel=(),
                           stride=(), dilate=(), pad=(), num_filter=1,
                           num_group=1, num_deformable_group=1,
                           no_bias=False, layout=None, workspace=1024):
    """Deformable convolution v1 (NCHW).

    data (B, C, H, W); offset (B, 2*G*kh*kw, Ho, Wo) with per-position
    (dy, dx) pairs, deformable groups G splitting the channels; weight
    (O, C/num_group, kh, kw). The sampled im2col volume contracts with
    the filters in ONE dot_general on the MXU.
    """
    b, c, h, w = data.shape
    kh, kw = kernel
    sh, sw = (stride or (1, 1))
    dh, dw = (dilate or (1, 1))
    ph, pw = (pad or (0, 0))
    ho = (h + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    wo = (w + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    g = num_deformable_group
    cg = c // g

    # base sampling grid (kh*kw, Ho, Wo)
    oy = jnp.arange(ho) * sh - ph
    ox = jnp.arange(wo) * sw - pw
    ky = jnp.arange(kh) * dh
    kx = jnp.arange(kw) * dw
    base_y = (oy[None, :, None] + ky.repeat(kw)[:, None, None]
              ).astype(jnp.float32)                    # (kh*kw, Ho, 1)
    base_x = (ox[None, None, :] + jnp.tile(kx, kh)[:, None, None]
              ).astype(jnp.float32)                    # (kh*kw, 1, Wo)
    off = offset.reshape(b, g, kh * kw, 2, ho, wo).astype(jnp.float32)
    sy = base_y[None, None] + off[:, :, :, 0]          # (B, G, K, Ho, Wo)
    sx = base_x[None, None] + off[:, :, :, 1]

    def per_image(img, sy_i, sx_i):
        # img (C, H, W) -> grouped (G, Cg, H, W)
        img_g = img.reshape(g, cg, h, w)

        def per_dgroup(img_gg, sy_g, sx_g):
            return _bilinear_gather(img_gg, sy_g, sx_g)  # (Cg, K, Ho, Wo)

        return jax.vmap(per_dgroup)(img_g, sy_i, sx_i)  # (G, Cg, K, Ho, Wo)

    vol = jax.vmap(per_image)(data.astype(jnp.float32), sy, sx)
    # (B, G, Cg, K, Ho, Wo) -> (B, C*K, Ho*Wo): the deformable im2col
    vol = vol.reshape(b, c, kh * kw, ho * wo)
    wmat = weight.reshape(num_filter, -1).astype(jnp.float32)
    if num_group == 1:
        col = vol.reshape(b, c * kh * kw, ho * wo)
        out = jnp.einsum("ok,bkp->bop", wmat, col)
    else:
        cpg = c // num_group
        opg = num_filter // num_group
        col = vol.reshape(b, num_group, cpg * kh * kw, ho * wo)
        wg = wmat.reshape(num_group, opg, cpg * kh * kw)
        out = jnp.einsum("gok,bgkp->bgop", wg, col).reshape(
            b, num_filter, ho * wo)
    out = out.reshape(b, num_filter, ho, wo).astype(data.dtype)
    if not no_bias and bias is not None:
        out = out + bias.astype(out.dtype).reshape(1, -1, 1, 1)
    return out


@register("Correlation", aliases=["correlation"])
def correlation(data1, data2, *, kernel_size=1, max_displacement=1,
                stride1=1, stride2=1, pad_size=0, is_multiply=True):
    """FlowNet correlation layer (cost volume) over NCHW pairs.

    Output (B, D*D, Ho, Wo) with D = 2*(max_displacement//stride2) + 1
    and displacements ``stride2 * (i - max_displacement//stride2)`` (the
    reference's neighborhood grid — always includes the zero shift):
    mean over channels and the kernel window of data1 . shifted(data2)
    (or |a - b| sums when ``is_multiply`` is False) — a shifted-product
    reduction XLA fuses; no gather kernels.
    """
    b, c, h, w = data1.shape
    p = int(pad_size)
    a = jnp.pad(data1.astype(jnp.float32),
                ((0, 0), (0, 0), (p, p), (p, p)))
    bb = jnp.pad(data2.astype(jnp.float32),
                 ((0, 0), (0, 0), (p, p), (p, p)))
    hp, wp = h + 2 * p, w + 2 * p
    k = int(kernel_size)
    kr = k // 2
    dmax = int(max_displacement)
    s1, s2 = int(stride1), int(stride2)
    radius = dmax // s2
    displacements = [s2 * (i - radius) for i in range(2 * radius + 1)]
    # output grid (reference formula)
    border = dmax + kr
    oy = jnp.arange(border, hp - border, s1)
    ox = jnp.arange(border, wp - border, s1)
    ho, wo = oy.shape[0], ox.shape[0]

    outs = []
    for dy in displacements:
        for dx in displacements:
            if is_multiply:
                prod = a * jnp.roll(bb, (-dy, -dx), axis=(2, 3))
            else:
                prod = jnp.abs(a - jnp.roll(bb, (-dy, -dx), axis=(2, 3)))
            # kernel-window mean via an avg pool of size k
            if k > 1:
                prod = jax.lax.reduce_window(
                    prod, 0.0, jax.lax.add, (1, 1, k, k), (1, 1, 1, 1),
                    "SAME")
            red = jnp.mean(prod, axis=1)               # (B, Hp, Wp)
            outs.append(red[:, oy][:, :, ox])
    out = jnp.stack(outs, axis=1) / (k * k if k > 1 else 1)
    return out.astype(data1.dtype)                     # (B, D*D, Ho, Wo)


@register("_contrib_PSROIPooling", aliases=["psroipooling"])
def psroi_pooling(data, rois, *, spatial_scale=1.0, output_dim=1,
                  pooled_size=7, group_size=0):
    """Position-sensitive ROI pooling (R-FCN).

    data (B, output_dim * group^2, H, W); rois (N, 5) [batch, x1, y1,
    x2, y2]. Each (ph, pw) output bin averages ITS OWN channel group —
    the position-sensitive trick — implemented as a dense per-bin
    average with static shapes (vmap over rois).
    """
    gs = int(group_size) or int(pooled_size)
    ps = int(pooled_size)
    b, cd, h, w = data.shape
    d = data.astype(jnp.float32).reshape(b, output_dim, gs, gs, h, w)

    def per_roi(roi):
        # reference semantics (psroi_pooling.cc): coords ROUND before
        # scaling; each bin averages the INTEGER pixels in
        # [floor(start), ceil(end)) — expressed densely with separable
        # 0/1 row/column masks so shapes stay static under jit
        bi = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1]) * spatial_scale
        y1 = jnp.round(roi[2]) * spatial_scale
        x2 = jnp.round(roi[3] + 1.0) * spatial_scale
        y2 = jnp.round(roi[4] + 1.0) * spatial_scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_h = rh / ps
        bin_w = rw / ps
        img = d[bi]                                    # (O, gs, gs, H, W)
        py = jnp.arange(ps)
        px = jnp.arange(ps)
        hstart = jnp.clip(jnp.floor(py * bin_h + y1), 0, h)
        hend = jnp.clip(jnp.ceil((py + 1) * bin_h + y1), 0, h)
        wstart = jnp.clip(jnp.floor(px * bin_w + x1), 0, w)
        wend = jnp.clip(jnp.ceil((px + 1) * bin_w + x1), 0, w)
        yy = jnp.arange(h)[None, :]
        xx = jnp.arange(w)[None, :]
        row_m = ((yy >= hstart[:, None]) & (yy < hend[:, None])
                 ).astype(jnp.float32)                 # (ps, H)
        col_m = ((xx >= wstart[:, None]) & (xx < wend[:, None])
                 ).astype(jnp.float32)                 # (ps, W)
        counts = (row_m.sum(-1)[:, None] * col_m.sum(-1)[None, :])
        gy = jnp.clip(py * gs // ps, 0, gs - 1)
        gx = jnp.clip(px * gs // ps, 0, gs - 1)
        # position-sensitive channel routing: bin (iy, ix) reads group
        # (gy[iy], gx[ix]); gather those (O, H, W) maps then reduce with
        # the separable masks
        grp = img[:, gy][:, :, gx]                     # (O, ps, ps, H, W)
        summed = jnp.einsum("oyxhw,yh,xw->oyx", grp, row_m, col_m)
        return summed / jnp.maximum(counts, 1.0)[None]

    out = jax.vmap(per_roi)(rois.astype(jnp.float32))
    return out.astype(data.dtype)                      # (N, O, ps, ps)
