"""``gluon.utils`` (reference: ``python/mxnet/gluon/utils.py`` ::
``split_data``/``split_and_load``/``clip_global_norm``/``check_sha1``/
``download``)."""
from __future__ import annotations

import hashlib
import math

from ..base import MXNetError
from ..ndarray import NDArray

__all__ = ["split_data", "split_and_load", "clip_global_norm",
           "check_sha1", "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Slice a batch along ``batch_axis`` into ``num_slice`` pieces
    (reference: utils.py::split_data)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice:
        raise MXNetError(
            f"cannot evenly split axis {batch_axis} of size {size} into "
            f"{num_slice} slices (set even_split=False)")
    if num_slice == 1:
        return [data]
    if size < num_slice:
        raise MXNetError(
            f"axis {batch_axis} of size {size} is smaller than "
            f"num_slice {num_slice}")
    # ALWAYS exactly num_slice slices (reference contract): the last
    # slice absorbs the remainder under even_split=False
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        lo = i * step
        hi = (i + 1) * step if i < num_slice - 1 else size
        idx = [slice(None)] * data.ndim
        idx[batch_axis] = slice(lo, hi)
        slices.append(data[tuple(idx)])
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split a batch and load each slice onto a context (reference:
    utils.py::split_and_load — the classic multi-device data feed)."""
    from ..ndarray import array as nd_array

    if not isinstance(data, NDArray):
        data = nd_array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale gradients so their GLOBAL L2 norm is <= max_norm
    (reference: utils.py::clip_global_norm). Returns the norm."""
    if not arrays:
        raise MXNetError("clip_global_norm: empty array list")
    total = 0.0
    for a in arrays:
        v = a.asnumpy().astype("float64")
        total += float((v * v).sum())
    norm = math.sqrt(total)
    if check_isfinite and not math.isfinite(norm):
        import warnings

        warnings.warn("nan or inf is detected. Clipping results will be "
                      "undefined.", stacklevel=2)
    scale = max_norm / (norm + 1e-8)
    if scale < 1.0:
        for a in arrays:
            a *= scale
    return norm


def check_sha1(filename, sha1_hash):
    """True iff the file's sha1 matches (reference: utils.py::check_sha1)."""
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            sha1.update(chunk)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None,
             retries=5, verify_ssl=True):
    """Offline environment: downloads are unavailable — raises with
    guidance (reference surface: utils.py::download)."""
    raise MXNetError(
        f"download({url!r}): this environment has no network egress. "
        "Place the file locally and pass its path to the consuming API "
        "(e.g. CustomEmbedding, ImageRecordIter).")
