"""Gluon Parameter and ParameterDict.

Reference: ``python/mxnet/gluon/parameter.py :: Parameter`` — deferred-shape
parameters, per-context data/grad copies, grad_req, lr_mult/wd_mult — and
``::ParameterDict`` (prefixing, shared params, save/load).

TPU-native notes: a parameter's payload is one NDArray per context for the
MXNet-compatible multi-device API, but the SPMD training path
(kvstore 'tpu_sync' / parallel.Mesh) keeps ONE array with a
`jax.sharding.NamedSharding` — per-device python copies are an anti-pattern
on TPU (SURVEY.md §2.4).
"""
from __future__ import annotations

from collections import OrderedDict
from typing import List, Optional

import numpy as _np

from .. import autograd, initializer
from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..ndarray import NDArray, array as nd_array, zeros as nd_zeros
from ..ndarray import ndarray as _ndarray_mod

__all__ = ["Parameter", "Constant", "ParameterDict",
           "DeferredInitializationError", "abstract_init"]

_ABSTRACT_INIT = [False]


class abstract_init:
    """Context: parameters initialize as zero-cost abstract placeholders.

    For AOT compilation of models too large to materialize on the host
    (e.g. validating an 8B-parameter sharded train step on a laptop-sized
    machine): inside the context, ``_finish_init`` records shape/dtype and
    stores abstract data instead of running the initializer. Such
    parameters cannot be read — only their shapes/dtypes feed
    ``jax.ShapeDtypeStruct``-based lowering (TrainStep.aot_compile).
    """

    def __enter__(self):
        self._prev = _ABSTRACT_INIT[0]
        _ABSTRACT_INIT[0] = True
        return self

    def __exit__(self, *exc):
        _ABSTRACT_INIT[0] = self._prev
        return False


class DeferredInitializationError(MXNetError):
    """Parameter accessed before its deferred shape was resolved
    (reference: parameter.py::DeferredInitializationError)."""


class Parameter:
    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self.name = name
        self._grad_req = grad_req if differentiable else "null"
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        # grad_stype='row_sparse' routes embedding weights through the
        # lazy row-update path (parallel.sparse_grad); storage itself
        # stays dense-backed (SURVEY.md §7.3.5)
        self.grad_stype = grad_stype
        self._stype = stype
        self._data: Optional[OrderedDict] = None  # Context -> NDArray
        self._grad: Optional[OrderedDict] = None
        self._deferred_init = None  # (init, ctx_list, default_init)
        self._trainer = None

    # ------------------------------------------------------------------
    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape)
            return
        # 0 is an unknown dim on EITHER side (deferred init / shared params
        # e.g. a tied Dense declaring (vocab, 0) over an embedding's
        # (vocab, units)); merge keeping the more specific size.
        if len(self._shape) != len(new_shape) or any(
            s != 0 and n != 0 and s != n
            for s, n in zip(self._shape, new_shape)
        ):
            raise MXNetError(
                f"Parameter {self.name}: cannot overwrite shape {self._shape} "
                f"with incompatible {tuple(new_shape)}")
        self._shape = tuple(s if n == 0 else n
                            for s, n in zip(self._shape, new_shape))

    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        assert req in ("write", "add", "null")
        if not self._differentiable:
            req = "null"
        if self._grad_req == req:
            return
        self._grad_req = req
        if self._data is not None:
            if req == "null":
                self._grad = None
                for arr in self._data.values():
                    arr.drop_grad()
            else:
                self._init_grad()

    # ------------------------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False) -> None:
        """Allocate and initialize on the given context(s)
        (reference: Parameter.initialize / _finish_deferred_init)."""
        if self._data is not None and not force_reinit:
            return
        default_init = default_init or initializer.Uniform()
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        if _ABSTRACT_INIT[0]:
            # abstract-AOT mode: defer even known-shape params so their
            # placeholder data is created inside the settle trace, where
            # the zeros are free abstract values (no 2 GB embedding tables
            # materializing on the host). The flag is CAPTURED here so the
            # param stays abstract even if it resolves after the
            # abstract_init context has exited (aot_compile's settle).
            self._deferred_init = (init, list(ctx), default_init, True)
            return
        if self._shape is None or any(s <= 0 for s in self._shape):
            if self.allow_deferred_init:
                self._deferred_init = (init, list(ctx), default_init, False)
                return
            raise MXNetError(
                f"cannot initialize Parameter {self.name} with unknown shape "
                f"{self._shape}; set allow_deferred_init=True or give the shape")
        self._finish_init(init, list(ctx), default_init)

    def _finish_init(self, init, ctx_list, default_init, abstract=False):
        import jax

        if abstract or _ABSTRACT_INIT[0]:
            # abstract placeholder: shape/dtype only, no initializer run —
            # inside a live trace the zeros are a free abstract value, and
            # the payload is only ever used as a slot (make_pure_fn swaps
            # real/traced values in before any read). EAGER resolution
            # (no live trace) would silently materialize dense zeros —
            # multi-GB for the weights this mode exists for, and all-zero
            # checkpoints if saved — so it is an error instead.
            import jax.numpy as jnp

            # live-trace probe: under omnistaging a 0-size zeros is a
            # tracer inside any trace and a concrete array outside
            if not isinstance(jnp.zeros((0,)), jax.core.Tracer):
                raise MXNetError(
                    f"Parameter {self.name} was built under "
                    "abstract_init() and holds no values; it can only be "
                    "used through TrainStep.aot_compile (eager reads "
                    "would materialize meaningless zeros)")
            self._data = OrderedDict(
                (c, NDArray(data=jnp.zeros(self._shape,
                                           dtype=str(self.dtype)), ctx=c))
                for c in ctx_list)
            self._deferred_init = None
            return
        # Deferred init can resolve while a trace is live (TrainStep's
        # eval_shape settle, hybridize tracing). Initializer values are
        # concrete by construction; ensure_compile_time_eval keeps the raw
        # jnp calls inside initializers/__setitem__ from being captured as
        # tracers by the surrounding trace.
        with jax.ensure_compile_time_eval():
            self._finish_init_concrete(init, ctx_list, default_init)

    def _finish_init_concrete(self, init, ctx_list, default_init):
        host = _np.zeros(self._shape, dtype="float32")
        host_nd = nd_array(host, ctx=cpu(0), dtype="float32")
        ini = initializer.create(init) if init is not None else initializer.create(self.init) if self.init is not None else default_init
        ini(initializer.InitDesc(self.name, global_init=ini), host_nd)
        self._data = OrderedDict()
        for c in ctx_list:
            self._data[c] = host_nd.copyto(c).astype(self.dtype, copy=False) \
                if str(self.dtype) != "float32" else host_nd.copyto(c)
        self._deferred_init = None
        if self._grad_req != "null":
            self._init_grad()

    def _finish_deferred_init(self, inferred_shape=None) -> None:
        if self._deferred_init is None:
            return
        if inferred_shape is not None:
            self.shape = inferred_shape
        if self._shape is None or any(s <= 0 for s in self._shape):
            raise DeferredInitializationError(
                f"Parameter {self.name} shape still unknown: {self._shape}")
        deferred = self._deferred_init
        if len(deferred) == 4:
            init, ctx_list, default_init, abstract = deferred
        else:  # legacy 3-tuple
            init, ctx_list, default_init = deferred
            abstract = False
        self._finish_init(init, ctx_list, default_init, abstract=abstract)

    def _init_grad(self):
        self._grad = OrderedDict()
        for c, arr in self._data.items():
            g = nd_zeros(arr.shape, ctx=c, dtype=str(arr.dtype))
            self._grad[c] = g
            autograd.mark_variables([arr], [g], self._grad_req)

    # ------------------------------------------------------------------
    def _check_initialized(self, ctx=None):
        if self._data is None:
            if self._deferred_init is not None:
                raise DeferredInitializationError(
                    f"Parameter {self.name} has not been initialized yet "
                    "(deferred shape); run a forward pass first")
            raise MXNetError(
                f"Parameter {self.name} has not been initialized; call "
                ".initialize() first")
        if ctx is not None and ctx not in self._data:
            raise MXNetError(
                f"Parameter {self.name} was not initialized on context {ctx}; "
                f"it lives on {list(self._data)}")

    def data(self, ctx: Optional[Context] = None) -> NDArray:
        if self._data is None and self._deferred_init is not None \
                and self._shape and all(s > 0 for s in self._shape):
            # known-shape deferred param resolves on first touch (covers
            # abstract_init, which defers everything)
            self._finish_deferred_init()
        self._check_initialized(ctx)
        if ctx is None:
            return next(iter(self._data.values()))
        return self._data[ctx]

    def list_data(self) -> List[NDArray]:
        self._check_initialized()
        return list(self._data.values())

    def grad(self, ctx: Optional[Context] = None) -> NDArray:
        self._check_initialized(ctx)
        if self._grad is None:
            raise MXNetError(
                f"Parameter {self.name} has grad_req='null'; no gradient buffer")
        if ctx is None:
            return next(iter(self._grad.values()))
        return self._grad[ctx]

    def list_grad(self) -> List[NDArray]:
        self._check_initialized()
        if self._grad is None:
            return []
        return list(self._grad.values())

    def list_ctx(self) -> List[Context]:
        self._check_initialized()
        return list(self._data.keys())

    def set_data(self, data) -> None:
        if self._data is None and self._deferred_init is not None:
            # setting data resolves a deferred parameter (load_parameters path)
            self.shape = data.shape
            self._finish_deferred_init()
        self._check_initialized()
        if tuple(data.shape) != tuple(self._shape):
            raise MXNetError(
                f"Parameter {self.name}: cannot set data of shape "
                f"{tuple(data.shape)} on parameter of shape {self._shape}")
        for c, arr in self._data.items():
            src = data if isinstance(data, NDArray) else nd_array(data, ctx=c)
            arr._set_data(src.as_in_context(c).astype(str(arr.dtype), copy=False).data)

    def zero_grad(self) -> None:
        if self._grad is None:
            return
        for g in self._grad.values():
            g[:] = 0

    def reset_ctx(self, ctx) -> None:
        if isinstance(ctx, Context):
            ctx = [ctx]
        self._check_initialized()
        cur = self.data()
        self._data = OrderedDict((c, cur.copyto(c)) for c in ctx)
        if self._grad_req != "null":
            self._init_grad()

    def cast(self, dtype) -> None:
        self.dtype = dtype
        if self._data is None:
            return
        self._data = OrderedDict(
            (c, arr.astype(dtype)) for c, arr in self._data.items())
        if self._grad is not None:
            self._init_grad()

    def var(self):
        from ..symbol import var

        return var(self.name, shape=self._shape, dtype=self.dtype)

    def __repr__(self):
        return f"Parameter {self.name} (shape={self._shape}, dtype={self.dtype})"


class Constant(Parameter):
    """Non-differentiable constant parameter (reference:
    parameter.py::Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = nd_array(value)
        self.value = value
        super().__init__(
            name, grad_req="null", shape=value.shape, dtype=str(value.dtype),
            init=initializer.Constant(value), differentiable=False)


class ParameterDict:
    """Prefix-scoped dict of Parameters (reference:
    parameter.py::ParameterDict)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = OrderedDict()
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def __getitem__(self, key):
        return self._params[key]

    def __contains__(self, key):
        return key in self._params

    def __repr__(self):
        lines = [f"{type(self).__name__} ({self._prefix}"]
        lines += [f"  {v}" for v in self.values()]
        return "\n".join(lines) + ")"

    def get(self, name, **kwargs) -> Parameter:
        """Find (or create) a parameter named prefix+name
        (reference: ParameterDict.get — also resolves shared params)."""
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if k == "shape" and v is not None:
                    param.shape = tuple(v)
                elif k == "init" and v is not None and param.init is None:
                    param.init = v
        return param

    def get_constant(self, name, value=None) -> Constant:
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            if value is None:
                raise MXNetError(f"no constant named {name} and no value given")
            param = Constant(name, value)
            self._params[name] = param
        return param

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared:
            self._params[name] = self._shared[name]
            return self._params[name]
        return None

    def update(self, other) -> None:
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise MXNetError(f"duplicate parameter name {k}")
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False) -> None:
        default = initializer.create(init) if init is not None \
            else initializer.Uniform()
        for p in self.values():
            p.initialize(None, ctx, default_init=default, force_reinit=force_reinit)

    def zero_grad(self):
        for p in self.values():
            p.zero_grad()

    def setattr(self, name, value):
        for p in self.values():
            setattr(p, name, value)

    def reset_ctx(self, ctx):
        for p in self.values():
            p.reset_ctx(ctx)

    def save(self, filename, strip_prefix="") -> None:
        from ..ndarray import serialization

        arg_dict = {}
        for p in self.values():
            name = p.name
            if strip_prefix and name.startswith(strip_prefix):
                name = name[len(strip_prefix):]
            arg_dict[name] = p.data().as_in_context(cpu(0))
        serialization.save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix="", cast_dtype=False,
             dtype_source="current") -> None:
        from ..ndarray import serialization

        loaded = serialization.load(filename)
        if isinstance(loaded, list):
            raise MXNetError("parameter file holds an unnamed list, not a dict")
        data = {}
        for k, v in loaded.items():
            if k.startswith(("arg:", "aux:")):
                k = k[4:]
            data[restore_prefix + k] = v
        if not allow_missing:
            for name in self.keys():
                if name not in data:
                    raise MXNetError(
                        f"Parameter {name} missing in file {filename}; set "
                        "allow_missing=True to skip")
        for name, v in data.items():
            if name not in self._params:
                if not ignore_extra:
                    raise MXNetError(
                        f"file {filename} has extra parameter {name}; set "
                        "ignore_extra=True to skip")
                continue
            p = self._params[name]
            if cast_dtype and dtype_source == "current" and p._data is not None:
                v = v.astype(str(p.dtype))
            elif cast_dtype and dtype_source == "saved":
                p.dtype = str(v.dtype)
            if ctx is not None and p._data is None and p._deferred_init is None:
                p.initialize(ctx=ctx, default_init=initializer.Zero())
            p.set_data(v)
