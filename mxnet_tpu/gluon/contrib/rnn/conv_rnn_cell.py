"""Convolutional recurrent cells (reference:
``python/mxnet/gluon/contrib/rnn/conv_rnn_cell.py``).

State and input are feature maps; the i2h/h2h projections are
convolutions, so recurrence preserves spatial structure (ConvLSTM,
Shi et al. 2015). Spatial dims come from ``input_shape`` at construction
— same contract as the reference (deferred spatial inference isn't
supported there either)."""
from __future__ import annotations

from ...rnn.rnn_cell import RecurrentCell

__all__ = ["Conv1DRNNCell", "Conv2DRNNCell", "Conv3DRNNCell",
           "Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
           "Conv1DGRUCell", "Conv2DGRUCell", "Conv3DGRUCell"]


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) != n:
            raise ValueError(f"expected length-{n} tuple, got {v}")
        return tuple(int(x) for x in v)
    return (int(v),) * n


class _BaseConvRNNCell(RecurrentCell):
    """Common machinery: conv i2h/h2h params + spatial state shape."""

    _num_gates = 1

    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad=0, i2h_dilate=1, h2h_dilate=1,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 dims=2, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._dims = dims
        self._input_shape = tuple(input_shape)     # (C, *spatial)
        if len(self._input_shape) != dims + 1:
            raise ValueError(
                f"input_shape must be (channels, *{dims} spatial dims), "
                f"got {input_shape}")
        self._channels = int(hidden_channels)
        self._i2h_kernel = _tuple(i2h_kernel, dims)
        self._h2h_kernel = _tuple(h2h_kernel, dims)
        for k in self._h2h_kernel:
            if k % 2 == 0:
                raise ValueError(
                    f"h2h_kernel must be odd (state shape must be "
                    f"preserved), got {self._h2h_kernel}")
        self._i2h_pad = _tuple(i2h_pad, dims)
        self._i2h_dilate = _tuple(i2h_dilate, dims)
        self._h2h_dilate = _tuple(h2h_dilate, dims)
        # h2h 'same' padding given dilation: d*(k-1)/2
        self._h2h_pad = tuple(d * (k - 1) // 2 for k, d in
                              zip(self._h2h_kernel, self._h2h_dilate))
        # output spatial dims of the i2h conv define the state shape
        in_c = self._input_shape[0]
        self._state_spatial = tuple(
            (s + 2 * p - d * (k - 1) - 1) + 1
            for s, p, d, k in zip(self._input_shape[1:], self._i2h_pad,
                                  self._i2h_dilate, self._i2h_kernel))
        ng = self._num_gates
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(ng * self._channels, in_c)
                + self._i2h_kernel, init=i2h_weight_initializer)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(ng * self._channels, self._channels)
                + self._h2h_kernel, init=h2h_weight_initializer)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(ng * self._channels,),
                init=i2h_bias_initializer)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(ng * self._channels,),
                init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        shape = (batch_size, self._channels) + self._state_spatial
        n_states = 2 if self._num_gates == 4 else 1
        return [{"shape": shape, "__layout__": "NC" + "DHW"[-self._dims:]}
                for _ in range(n_states)]

    def _conv_pair(self, F, inputs, state_h, i2h_weight, h2h_weight,
                   i2h_bias, h2h_bias):
        ng = self._num_gates
        i2h = F.Convolution(inputs, i2h_weight, i2h_bias,
                            kernel=self._i2h_kernel, pad=self._i2h_pad,
                            dilate=self._i2h_dilate,
                            num_filter=ng * self._channels)
        h2h = F.Convolution(state_h, h2h_weight, h2h_bias,
                            kernel=self._h2h_kernel, pad=self._h2h_pad,
                            dilate=self._h2h_dilate,
                            num_filter=ng * self._channels)
        return i2h, h2h


class _ConvRNNCell(_BaseConvRNNCell):
    _num_gates = 1

    def _alias(self):
        return "conv_rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_pair(F, inputs, states[0], i2h_weight,
                                   h2h_weight, i2h_bias, h2h_bias)
        out = F.Activation(i2h + h2h, act_type="tanh")
        return out, [out]


class _ConvLSTMCell(_BaseConvRNNCell):
    _num_gates = 4

    def _alias(self):
        return "conv_lstm"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_pair(F, inputs, states[0], i2h_weight,
                                   h2h_weight, i2h_bias, h2h_bias)
        gates = i2h + h2h
        in_g, forget_g, in_t, out_g = F.split(gates, num_outputs=4, axis=1)
        in_g = F.sigmoid(in_g)
        forget_g = F.sigmoid(forget_g)
        in_t = F.Activation(in_t, act_type="tanh")
        out_g = F.sigmoid(out_g)
        next_c = forget_g * states[1] + in_g * in_t
        next_h = out_g * F.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class _ConvGRUCell(_BaseConvRNNCell):
    _num_gates = 3

    def _alias(self):
        return "conv_gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_pair(F, inputs, states[0], i2h_weight,
                                   h2h_weight, i2h_bias, h2h_bias)
        i2h_r, i2h_z, i2h_n = F.split(i2h, num_outputs=3, axis=1)
        h2h_r, h2h_z, h2h_n = F.split(h2h, num_outputs=3, axis=1)
        reset = F.sigmoid(i2h_r + h2h_r)
        update = F.sigmoid(i2h_z + h2h_z)
        new = F.Activation(i2h_n + reset * h2h_n, act_type="tanh")
        next_h = (1.0 - update) * new + update * states[0]
        return next_h, [next_h]


def _make(cls, dims, name):
    def __init__(self, input_shape, hidden_channels, i2h_kernel,
                 h2h_kernel, **kwargs):
        cls.__init__(self, input_shape, hidden_channels, i2h_kernel,
                     h2h_kernel, dims=dims, **kwargs)

    return type(name, (cls,), {"__init__": __init__,
                               "__doc__": f"{dims}-D {cls.__doc__}"})


Conv1DRNNCell = _make(_ConvRNNCell, 1, "Conv1DRNNCell")
Conv2DRNNCell = _make(_ConvRNNCell, 2, "Conv2DRNNCell")
Conv3DRNNCell = _make(_ConvRNNCell, 3, "Conv3DRNNCell")
Conv1DLSTMCell = _make(_ConvLSTMCell, 1, "Conv1DLSTMCell")
Conv2DLSTMCell = _make(_ConvLSTMCell, 2, "Conv2DLSTMCell")
Conv3DLSTMCell = _make(_ConvLSTMCell, 3, "Conv3DLSTMCell")
Conv1DGRUCell = _make(_ConvGRUCell, 1, "Conv1DGRUCell")
Conv2DGRUCell = _make(_ConvGRUCell, 2, "Conv2DGRUCell")
Conv3DGRUCell = _make(_ConvGRUCell, 3, "Conv3DGRUCell")
