"""VariationalDropoutCell (reference:
``python/mxnet/gluon/contrib/rnn/rnn_cell.py`` ::
``VariationalDropoutCell``) — Gal & Ghahramani (2016): ONE dropout mask
per sequence, reused across every timestep, applied to inputs / states /
outputs independently."""
from __future__ import annotations

from ...rnn.rnn_cell import ModifierCell

__all__ = ["VariationalDropoutCell"]


class VariationalDropoutCell(ModifierCell):
    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0):
        self._alias_name = "vardrop"
        super().__init__(base_cell)
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    def _alias(self):
        return "vardrop"

    def reset(self):
        super().reset()
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    def _mask(self, F, which, rate, like):
        """Sample the per-sequence mask lazily at the first step, then
        reuse it — the variational-RNN contract."""
        mask = getattr(self, which)
        if mask is None:
            mask = F.Dropout(F.ones_like(like), p=rate)
            setattr(self, which, mask)
        return mask

    def hybrid_forward(self, F, inputs, states):
        from .... import autograd

        training = autograd.is_training()
        if training and self.drop_inputs:
            inputs = inputs * self._mask(F, "_input_mask",
                                         self.drop_inputs, inputs)
        if training and self.drop_states:
            mask = self._mask(F, "_state_mask", self.drop_states, states[0])
            states = [states[0] * mask] + list(states[1:])
        output, next_states = self.base_cell(inputs, states)
        if training and self.drop_outputs:
            output = output * self._mask(F, "_output_mask",
                                         self.drop_outputs, output)
        return output, next_states

    def __repr__(self):
        return (f"VariationalDropoutCell(in={self.drop_inputs}, "
                f"state={self.drop_states}, out={self.drop_outputs}, "
                f"base={self.base_cell.__class__.__name__})")
