"""``gluon.contrib.rnn`` — convolutional RNN cells + VariationalDropout
(reference: ``python/mxnet/gluon/contrib/rnn/conv_rnn_cell.py`` ::
``_BaseConvRNNCell``/``Conv{1,2,3}D{RNN,LSTM,GRU}Cell`` and
``rnn_cell.py::VariationalDropoutCell``)."""
from .conv_rnn_cell import (Conv1DRNNCell, Conv2DRNNCell, Conv3DRNNCell,
                            Conv1DLSTMCell, Conv2DLSTMCell, Conv3DLSTMCell,
                            Conv1DGRUCell, Conv2DGRUCell, Conv3DGRUCell)
from .rnn_cell import VariationalDropoutCell

__all__ = ["Conv1DRNNCell", "Conv2DRNNCell", "Conv3DRNNCell",
           "Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
           "Conv1DGRUCell", "Conv2DGRUCell", "Conv3DGRUCell",
           "VariationalDropoutCell"]
