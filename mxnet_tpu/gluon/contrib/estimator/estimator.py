"""Estimator — a batteries-included fit loop.

Reference: ``python/mxnet/gluon/contrib/estimator/estimator.py`` —
Estimator(net, loss, train_metrics, trainer, context) with
fit(train_data, val_data, epochs) driving the event-handler protocol.
"""
from __future__ import annotations

from typing import List, Optional

from .... import autograd, metric as metric_mod
from ....base import MXNetError
from ....context import Context, cpu, current_context
from ...trainer import Trainer
from .event_handler import (BatchBegin, BatchEnd, EpochBegin, EpochEnd,
                            LoggingHandler, MetricHandler, StoppingHandler,
                            TrainBegin, TrainEnd, ValidationHandler)

__all__ = ["Estimator"]


class Estimator:
    def __init__(self, net, loss, train_metrics=None, trainer=None,
                 context=None, val_metrics=None):
        self.net = net
        self.loss = loss
        self.train_metrics = [metric_mod.create(m) for m in (train_metrics or ["accuracy"])]
        self.val_metrics = [metric_mod.create(m) for m in (val_metrics or ["accuracy"])]
        self.context = self._check_context(context)
        self.trainer = trainer or Trainer(
            net.collect_params(), "sgd", {"learning_rate": 0.001})
        self.max_epoch = None
        self.max_batch = None

    def _check_context(self, context):
        if context is None:
            return [current_context()]
        if isinstance(context, Context):
            return [context]
        return list(context)

    def evaluate(self, val_data, val_metrics=None, batch_axis=0):
        val_metrics = val_metrics or self.val_metrics
        for m in val_metrics:
            m.reset()
        for batch in val_data:
            data, label = batch[0], batch[1]
            data = data.as_in_context(self.context[0])
            label = label.as_in_context(self.context[0])
            pred = self.net(data)
            for m in val_metrics:
                m.update([label], [pred])
        return val_metrics

    def fit(self, train_data, val_data=None, epochs=None, event_handlers=None,
            batches=None, batch_axis=0):
        self.max_epoch = epochs
        self.max_batch = batches
        if epochs is None and batches is None:
            raise MXNetError("must specify epochs or batches")
        handlers = self._prepare_handlers(val_data, event_handlers)
        train_begin = [h for h in handlers if isinstance(h, TrainBegin)]
        epoch_begin = [h for h in handlers if isinstance(h, EpochBegin)]
        batch_begin = [h for h in handlers if isinstance(h, BatchBegin)]
        batch_end = [h for h in handlers if isinstance(h, BatchEnd)]
        epoch_end = [h for h in handlers if isinstance(h, EpochEnd)]
        train_end = [h for h in handlers if isinstance(h, TrainEnd)]

        for h in train_begin:
            h.train_begin(self)
        stop = False
        while not stop:
            for h in epoch_begin:
                h.epoch_begin(self)
            for batch in train_data:
                data, label = batch[0], batch[1]
                data = data.as_in_context(self.context[0])
                label = label.as_in_context(self.context[0])
                for h in batch_begin:
                    h.batch_begin(self, batch=batch)
                with autograd.record():
                    pred = self.net(data)
                    loss = self.loss(pred, label)
                loss.backward()
                self.trainer.step(data.shape[0])
                for h in batch_end:
                    if h.batch_end(self, batch=batch, pred=[pred],
                                   label=[label], loss=[loss]):
                        stop = True
                if stop:
                    break
            for h in epoch_end:
                if h.epoch_end(self):
                    stop = True
        for h in train_end:
            h.train_end(self)

    def _prepare_handlers(self, val_data, event_handlers):
        handlers = list(event_handlers or [])
        if not any(isinstance(h, StoppingHandler) for h in handlers):
            handlers.append(StoppingHandler(self.max_epoch, self.max_batch))
        if not any(isinstance(h, MetricHandler) for h in handlers):
            handlers.append(MetricHandler(self.train_metrics))
        if val_data is not None and not any(
                isinstance(h, ValidationHandler) for h in handlers):
            handlers.append(ValidationHandler(val_data, self.evaluate,
                                              self.val_metrics))
        if not any(isinstance(h, LoggingHandler) for h in handlers):
            handlers.append(LoggingHandler(train_metrics=self.train_metrics,
                                           val_metrics=self.val_metrics))
        return handlers
