"""Estimator event handlers.

Reference: ``python/mxnet/gluon/contrib/estimator/event_handler.py`` —
the TrainBegin/TrainEnd/EpochBegin/EpochEnd/BatchBegin/BatchEnd mixin
protocol plus StoppingHandler, MetricHandler, ValidationHandler,
LoggingHandler, CheckpointHandler, EarlyStoppingHandler.
"""
from __future__ import annotations

import logging
import os
import time

import numpy as _np

__all__ = ["TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd", "BatchBegin",
           "BatchEnd", "StoppingHandler", "MetricHandler",
           "ValidationHandler", "LoggingHandler", "CheckpointHandler",
           "EarlyStoppingHandler"]


class TrainBegin:
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd:
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin:
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd:
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin:
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd:
    def batch_end(self, estimator, *args, **kwargs):
        pass


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self.current_batch = 0
        self.current_epoch = 0
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self.max_epoch = estimator.max_epoch if self.max_epoch is None else self.max_epoch
        self.max_batch = estimator.max_batch if self.max_batch is None else self.max_batch

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.max_batch is not None and self.current_batch == self.max_batch:
            self.stop_training = True
        return self.stop_training

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.max_epoch is not None and self.current_epoch == self.max_epoch:
            self.stop_training = True
        return self.stop_training


class MetricHandler(EpochBegin, BatchEnd):
    def __init__(self, train_metrics):
        self.train_metrics = train_metrics or []

    def epoch_begin(self, estimator, *args, **kwargs):
        for metric in self.train_metrics:
            metric.reset()

    def batch_end(self, estimator, *args, **kwargs):
        pred = kwargs["pred"]
        label = kwargs["label"]
        loss = kwargs["loss"]
        from ....metric import Loss as LossMetric

        for metric in self.train_metrics:
            if isinstance(metric, LossMetric):
                metric.update(0, loss)
            else:
                metric.update(label, pred)


class ValidationHandler(TrainBegin, BatchEnd, EpochEnd):
    def __init__(self, val_data, eval_fn, val_metrics=None, epoch_period=1,
                 batch_period=None):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.val_metrics = val_metrics or []
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self.eval_fn(val_data=self.val_data, val_metrics=self.val_metrics)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            self.eval_fn(val_data=self.val_data, val_metrics=self.val_metrics)


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchBegin,
                     BatchEnd):
    def __init__(self, log_interval="epoch", train_metrics=None,
                 val_metrics=None):
        self.log_interval = log_interval
        self.train_metrics = train_metrics or []
        self.val_metrics = val_metrics or []
        self.batch_index = 0
        self.current_epoch = 0
        self.processed_samples = 0
        self.logger = logging.getLogger("mxnet_tpu.estimator")

    def train_begin(self, estimator, *args, **kwargs):
        self.train_start = time.time()
        self.logger.info("Training begin")

    def train_end(self, estimator, *args, **kwargs):
        t = time.time() - self.train_start
        self.logger.info("Train finished using %.3fs", t)

    def epoch_begin(self, estimator, *args, **kwargs):
        self.epoch_start = time.time()

    def epoch_end(self, estimator, *args, **kwargs):
        t = time.time() - self.epoch_start
        msgs = [f"{m.get()[0]}: {m.get()[1]:.4f}"
                for m in self.train_metrics + self.val_metrics]
        self.logger.info("Epoch %d finished in %.3fs: %s",
                         self.current_epoch, t, ", ".join(msgs))
        self.current_epoch += 1
        self.batch_index = 0

    def batch_end(self, estimator, *args, **kwargs):
        if isinstance(self.log_interval, int) \
                and self.batch_index % self.log_interval == 0:
            msgs = [f"{m.get()[0]}: {m.get()[1]:.4f}" for m in self.train_metrics]
            self.logger.info("Epoch %d batch %d: %s", self.current_epoch,
                             self.batch_index, ", ".join(msgs))
        self.batch_index += 1


class CheckpointHandler(TrainBegin, BatchEnd, EpochEnd):
    def __init__(self, model_dir, model_prefix="model", monitor=None,
                 verbose=0, save_best=False, mode="auto", epoch_period=1,
                 batch_period=None, max_checkpoints=5, resume_from_checkpoint=False):
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.monitor = monitor
        self.save_best = save_best
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.current_epoch = 0
        self.current_batch = 0
        self.best = None
        self.mode = mode
        os.makedirs(model_dir, exist_ok=True)

    def _save(self, estimator, tag):
        path = os.path.join(self.model_dir, f"{self.model_prefix}-{tag}.params")
        estimator.net.save_parameters(path)
        if estimator.trainer is not None:
            estimator.trainer.save_states(path.replace(".params", ".states"))

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self._save(estimator, f"batch{self.current_batch}")

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            self._save(estimator, f"epoch{self.current_epoch}")
        if self.save_best and self.monitor is not None:
            name, value = self.monitor.get()
            better = (self.best is None
                      or (self.mode != "min" and value > self.best)
                      or (self.mode == "min" and value < self.best))
            if better:
                self.best = value
                self._save(estimator, "best")


class EarlyStoppingHandler(TrainBegin, EpochEnd, TrainEnd):
    def __init__(self, monitor, min_delta=0, patience=0, mode="auto",
                 baseline=None):
        self.monitor = monitor
        self.min_delta = min_delta
        self.patience = patience
        self.mode = mode
        self.baseline = baseline
        self.wait = 0
        self.best = None
        self.stopped_epoch = 0
        self.current_epoch = 0
        self.stop_training = False

    def epoch_end(self, estimator, *args, **kwargs):
        name, value = self.monitor.get()
        if _np.isnan(value):
            self.current_epoch += 1
            return self.stop_training
        greater_is_better = self.mode != "min" and ("acc" in name or self.mode == "max")
        if self.best is None:
            self.best = value
        else:
            improved = (value > self.best + self.min_delta if greater_is_better
                        else value < self.best - self.min_delta)
            if improved:
                self.best = value
                self.wait = 0
            else:
                self.wait += 1
                if self.wait >= self.patience:
                    self.stopped_epoch = self.current_epoch
                    self.stop_training = True
        self.current_epoch += 1
        return self.stop_training

    def train_end(self, estimator, *args, **kwargs):
        if self.stop_training:
            logging.getLogger("mxnet_tpu.estimator").info(
                "Early stopping at epoch %d", self.stopped_epoch)
