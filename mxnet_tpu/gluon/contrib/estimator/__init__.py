"""Estimator fit-loop + event handlers (reference:
python/mxnet/gluon/contrib/estimator/)."""
from .estimator import Estimator  # noqa: F401
from .event_handler import (CheckpointHandler, EarlyStoppingHandler,  # noqa: F401
                            EpochBegin, EpochEnd, LoggingHandler,
                            MetricHandler, StoppingHandler, TrainBegin,
                            TrainEnd, ValidationHandler)
