"""Contrib layers.

Reference: ``python/mxnet/gluon/contrib/nn/basic_layers.py`` —
SyncBatchNorm, HybridConcurrent, Concurrent, Identity, SparseEmbedding,
PixelShuffle.
"""
from __future__ import annotations

from ... import nn as _nn
from ...block import Block, HybridBlock
from ...nn.basic_layers import BatchNorm

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SyncBatchNorm",
           "PixelShuffle2D"]


class Concurrent(Block):
    """Parallel branches concatenated (reference: contrib Concurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        from .... import ndarray as F

        out = [block(x) for block in self._children.values()]
        return F.concat(*out, dim=self.axis)


class HybridConcurrent(HybridBlock):
    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x):
        out = [block(x) for block in self._children.values()]
        return F.concat(*out, dim=self.axis)


class Identity(HybridBlock):
    def hybrid_forward(self, F, x):
        return x


class SyncBatchNorm(BatchNorm):
    """Cross-device batch norm (reference:
    src/operator/contrib/sync_batch_norm.cc + gluon contrib wrapper).

    TPU-native: under pjit/shard_map data parallelism, batch statistics are
    global when computed inside the sharded graph with a `psum` mean — the
    parallel.Mesh data-parallel step does exactly that, so this class only
    needs to flag the intent; on a single device it equals BatchNorm
    (SURVEY.md §2.4 row SyncBatchNorm: "lax.pmean of moments — trivial on
    TPU").
    """

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True, use_global_stats=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", **kwargs):
        super().__init__(
            axis=1, momentum=momentum, epsilon=epsilon, center=center,
            scale=scale, use_global_stats=use_global_stats,
            beta_initializer=beta_initializer,
            gamma_initializer=gamma_initializer,
            running_mean_initializer=running_mean_initializer,
            running_variance_initializer=running_variance_initializer,
            in_channels=in_channels, **kwargs)
        self._num_devices = num_devices


class PixelShuffle2D(HybridBlock):
    def __init__(self, factor):
        super().__init__()
        self._factor = factor if isinstance(factor, int) else factor[0]

    def hybrid_forward(self, F, x):
        return F.depth_to_space(x, block_size=self._factor)
