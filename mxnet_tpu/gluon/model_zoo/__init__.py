"""Model zoo (reference: python/mxnet/gluon/model_zoo/ for vision; the nlp
package covers the GluonNLP-zoo capability — SURVEY.md §1 L8)."""
from . import model_store  # noqa: F401
from . import vision  # noqa: F401
from . import nlp  # noqa: F401
