"""Gluon vision model zoo.

Parity surface: python/mxnet/gluon/model_zoo/vision/__init__.py::get_model —
resnet v1/v2 (18-152), vgg (11-19, +bn), alexnet, densenet (121-201),
squeezenet (1.0/1.1), inception-v3, mobilenet v1/v2 (4 multipliers each),
plus mobilenet-v3 small/large (GluonCV milestone capability).

``pretrained=True`` resolves weights through ``model_store`` (sha1-verified
cache; ``$MXNET_GLUON_REPO`` may be an ``http(s)://`` or ``file://`` repo,
so air-gapped hosts serve weights from a shared filesystem).
"""
from __future__ import annotations

from . import alexnet as _alexnet
from . import densenet as _densenet
from . import inception as _inception
from . import mobilenet as _mobilenet
from . import resnet as _resnet
from . import squeezenet as _squeezenet
from . import vgg as _vgg

from .alexnet import *  # noqa: F401,F403
from .densenet import *  # noqa: F401,F403
from .inception import *  # noqa: F401,F403
from .mobilenet import *  # noqa: F401,F403
from .resnet import *  # noqa: F401,F403
from .squeezenet import *  # noqa: F401,F403
from .vgg import *  # noqa: F401,F403
from .ssd import SSD, SSDMultiBoxLoss, get_ssd, ssd_toy  # noqa: F401

_models = {
    "ssd_toy": ssd_toy,
    "resnet18_v1": _resnet.resnet18_v1,
    "resnet34_v1": _resnet.resnet34_v1,
    "resnet50_v1": _resnet.resnet50_v1,
    "resnet101_v1": _resnet.resnet101_v1,
    "resnet152_v1": _resnet.resnet152_v1,
    "resnet18_v2": _resnet.resnet18_v2,
    "resnet34_v2": _resnet.resnet34_v2,
    "resnet50_v2": _resnet.resnet50_v2,
    "resnet101_v2": _resnet.resnet101_v2,
    "resnet152_v2": _resnet.resnet152_v2,
    "vgg11": _vgg.vgg11,
    "vgg13": _vgg.vgg13,
    "vgg16": _vgg.vgg16,
    "vgg19": _vgg.vgg19,
    "vgg11_bn": _vgg.vgg11_bn,
    "vgg13_bn": _vgg.vgg13_bn,
    "vgg16_bn": _vgg.vgg16_bn,
    "vgg19_bn": _vgg.vgg19_bn,
    "alexnet": _alexnet.alexnet,
    "densenet121": _densenet.densenet121,
    "densenet161": _densenet.densenet161,
    "densenet169": _densenet.densenet169,
    "densenet201": _densenet.densenet201,
    "squeezenet1.0": _squeezenet.squeezenet1_0,
    "squeezenet1.1": _squeezenet.squeezenet1_1,
    "inceptionv3": _inception.inception_v3,
    "mobilenet1.0": _mobilenet.mobilenet1_0,
    "mobilenet0.75": _mobilenet.mobilenet0_75,
    "mobilenet0.5": _mobilenet.mobilenet0_5,
    "mobilenet0.25": _mobilenet.mobilenet0_25,
    "mobilenetv2_1.0": _mobilenet.mobilenet_v2_1_0,
    "mobilenetv2_0.75": _mobilenet.mobilenet_v2_0_75,
    "mobilenetv2_0.5": _mobilenet.mobilenet_v2_0_5,
    "mobilenetv2_0.25": _mobilenet.mobilenet_v2_0_25,
    "mobilenetv3_large": _mobilenet.mobilenet_v3_large,
    "mobilenetv3_small": _mobilenet.mobilenet_v3_small,
}


def get_model(name, **kwargs):
    """Return a model by name (reference: vision/__init__.py::get_model)."""
    from ....base import MXNetError

    name = name.lower()
    if name not in _models:
        raise MXNetError(
            f"Model {name!r} is not supported. Available: {sorted(_models)}")
    return _models[name](**kwargs)
