"""Vision model zoo — populated in the model-zoo milestone."""
_models = {}


def get_model(name, **kwargs):
    from ....base import MXNetError

    name = name.lower()
    if name not in _models:
        raise MXNetError(
            f"model {name!r} is not in the zoo yet; available: {sorted(_models)}")
    return _models[name](**kwargs)
