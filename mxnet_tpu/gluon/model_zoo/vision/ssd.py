"""SSD single-shot detector family (reference capability: the SSD stack —
``example/ssd`` + GluonCV ``ssd_*`` models — built on the multibox op
trio ``src/operator/contrib/multibox_{prior,target,detection}.cc``).

TPU-first shape discipline: anchors/predictions are fixed-size per input
resolution (mask-based padding everywhere), so the whole detector —
backbone, heads, target assignment, and NMS — jits into single
executables for both the training step and inference.
"""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn
from ...loss import Loss

__all__ = ["SSD", "SSDMultiBoxLoss", "get_ssd", "ssd_toy"]


def _feature_trunk(base, pretrained_stages=None):
    """A small downsampling trunk; SSD taps it at several strides."""
    trunk = nn.HybridSequential(prefix="trunk_")
    with trunk.name_scope():
        filters = {"toy": (16, 32, 64), "small": (32, 64, 128)}[base]
        for f in filters:
            trunk.add(nn.Conv2D(f, 3, strides=2, padding=1),
                      nn.BatchNorm(), nn.Activation("relu"))
    return trunk


class SSD(HybridBlock):
    """Multi-scale SSD head over a trunk (reference: example/ssd
    symbol_builder + GluonCV model_zoo.ssd.SSD).

    forward(x) -> (anchors (1, N, 4), cls_preds (B, N, C+1),
    box_preds (B, N*4)); ``detect(x)`` decodes + NMS to (B, N, 6).
    """

    def __init__(self, num_classes, base="toy", num_scales=3,
                 sizes=None, ratios=None, nms_threshold=0.45,
                 nms_topk=400, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.num_classes = num_classes
        self.nms_threshold = nms_threshold
        self.nms_topk = nms_topk
        if sizes is None:
            # linearly spaced scales per feature map (SSD paper recipe)
            sizes = [(0.2 + 0.6 * i / num_scales,
                      0.2 + 0.6 * (i + 0.5) / num_scales)
                     for i in range(num_scales)]
        if ratios is None:
            ratios = [(1.0, 2.0, 0.5)] * num_scales
        self._sizes = sizes
        self._ratios = ratios
        with self.name_scope():
            self.trunk = _feature_trunk(base)
            self.stages = []
            self.cls_heads = []
            self.box_heads = []
            for i in range(num_scales):
                a = len(sizes[i]) + len(ratios[i]) - 1
                if i > 0:
                    stage = nn.HybridSequential(prefix=f"stage{i}_")
                    with stage.name_scope():
                        stage.add(nn.Conv2D(64, 3, strides=2, padding=1),
                                  nn.BatchNorm(), nn.Activation("relu"))
                    self.register_child(stage, f"stage{i}")
                    self.stages.append(stage)
                ch = nn.Conv2D(a * (num_classes + 1), 3, padding=1,
                               prefix=f"cls{i}_")
                bh = nn.Conv2D(a * 4, 3, padding=1, prefix=f"box{i}_")
                self.register_child(ch, f"cls_head{i}")
                self.register_child(bh, f"box_head{i}")
                self.cls_heads.append(ch)
                self.box_heads.append(bh)

    def hybrid_forward(self, F, x):
        feats = [self.trunk(x)]
        for stage in self.stages:
            feats.append(stage(feats[-1]))
        anchors, cls_preds, box_preds = [], [], []
        for feat, ch, bh, sz, rt in zip(feats, self.cls_heads,
                                        self.box_heads, self._sizes,
                                        self._ratios):
            anchors.append(F.contrib.MultiBoxPrior(
                feat, sizes=tuple(sz), ratios=tuple(rt)))
            # (B, A*(C+1), H, W) -> (B, H*W*A, C+1); reshape code 0 keeps
            # the batch dim symbolic (export/Symbol trace has no concrete
            # batch size)
            cp = F.Reshape(ch(feat).transpose((0, 2, 3, 1)),
                           shape=(0, -1, self.num_classes + 1))
            bp = F.Reshape(bh(feat).transpose((0, 2, 3, 1)),
                           shape=(0, -1))
            cls_preds.append(cp)
            box_preds.append(bp)
        return (F.concat(*anchors, dim=1),
                F.concat(*cls_preds, dim=1),
                F.concat(*box_preds, dim=1))

    def targets(self, anchors, labels, cls_preds,
                negative_mining_ratio=3.0):
        """MultiBoxTarget with the class-axis layout the op expects."""
        from .... import ndarray as F

        return F.contrib.MultiBoxTarget(
            anchors, labels, cls_preds.transpose((0, 2, 1)),
            negative_mining_ratio=negative_mining_ratio)

    def detect(self, x, threshold=0.01):
        """Inference: decode + per-class NMS -> (B, N, 6) rows
        [cls_id, score, x1, y1, x2, y2] (-1 = suppressed)."""
        from .... import ndarray as F

        anchors, cls_preds, box_preds = self(x)
        cls_prob = F.softmax(cls_preds, axis=-1).transpose((0, 2, 1))
        return F.contrib.MultiBoxDetection(
            cls_prob, box_preds, anchors, threshold=threshold,
            nms_threshold=self.nms_threshold, nms_topk=self.nms_topk)


class SSDMultiBoxLoss(Loss):
    """Classification CE (with hard-negative-mined targets) + smooth-L1
    localization (reference: GluonCV SSDMultiBoxLoss)."""

    def __init__(self, lambd=1.0, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._lambd = lambd

    def hybrid_forward(self, F, cls_preds, loc_preds, cls_target,
                       loc_target, loc_mask):
        # cls: ignore_label rows (-1) are masked out
        valid = cls_target >= 0
        logp = F.log_softmax(cls_preds, axis=-1)
        picked = F.pick(logp, F.maximum(cls_target, 0), axis=-1)
        n_pos = F.maximum(F.sum(cls_target > 0), 1.0)
        cls_loss = -F.sum(F.where(valid, picked,
                                  F.zeros_like(picked))) / n_pos
        loc_loss = F.sum(F.smooth_l1(
            (loc_preds - loc_target) * loc_mask, scalar=1.0)) / n_pos
        total = cls_loss + self._lambd * loc_loss
        if self._weight is not None:
            total = total * self._weight
        return total


def get_ssd(num_classes, base="toy", **kwargs):
    return SSD(num_classes, base=base, **kwargs)


def ssd_toy(num_classes=4, **kwargs):
    """Test-sized SSD (CI / examples)."""
    return SSD(num_classes, base="toy", **kwargs)
