"""Multi-head attention blocks.

Reference capability: GluonNLP's `MultiHeadAttentionCell` built on MXNet's
fused kernels (`src/operator/contrib/transformer.cc ::
_contrib_interleaved_matmul_selfatt_qk/_valatt`). TPU-native re-design: one
fused QKV projection (a single MXU matmul instead of three), the
`_contrib_sdp_attention` op for the core (f32 softmax statistics, Pallas
flash path on TPU), and an output projection. Head splitting is pure
reshape/transpose, which XLA folds into the surrounding matmuls.
"""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn

__all__ = ["MultiHeadAttention"]


class MultiHeadAttention(HybridBlock):
    """Self- or cross-attention with ``num_heads`` heads.

    Inputs: ``query`` (B, Lq, U); ``memory`` optional (B, Lk, U) for
    cross-attention (defaults to query = self-attention); ``mask`` optional,
    broadcastable to (B, heads, Lq, Lk), 1 = attend.
    """

    def __init__(self, units, num_heads, dropout=0.0, use_bias=True,
                 causal=False, cross=False, ring_axis=None,
                 attn_dropout=0.0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if units % num_heads:
            raise ValueError(f"units {units} not divisible by heads {num_heads}")
        self._units = units
        self._num_heads = num_heads
        self._causal = causal
        self._cross = cross
        # attention-probability dropout (reference: GluonNLP
        # MultiHeadAttentionCell's dropout on the attention weights) —
        # applied INSIDE sdp_attention / the flash kernels; ``dropout``
        # stays the output-projection dropout as before
        self._attn_dropout = float(attn_dropout)
        # sequence-parallel ring attention over this mesh axis (long
        # contexts; requires mask-free attention)
        self._ring_axis = ring_axis
        with self.name_scope():
            if cross:
                self.q_proj = nn.Dense(units, flatten=False,
                                       use_bias=use_bias, prefix="q_")
                self.kv_proj = nn.Dense(2 * units, flatten=False,
                                        use_bias=use_bias, prefix="kv_")
            else:
                # fused QKV: one MXU matmul instead of three
                self.qkv_proj = nn.Dense(3 * units, flatten=False,
                                         use_bias=use_bias, prefix="qkv_")
            self.out_proj = nn.Dense(units, flatten=False, use_bias=use_bias,
                                     prefix="out_")
            self.dropout = nn.Dropout(dropout) if dropout else None

    def _split_heads(self, F, x):
        # (B, L, U) -> (B, H, L, D) — the Pallas kernel's layout (Mosaic
        # tiles (L, D); a squeezed-H blhd tile is not lowerable, see
        # flash_shape_supported). XLA folds these transposes into the
        # surrounding matmuls where it can.
        b, l = x.shape[0], x.shape[1]
        h, d = self._num_heads, self._units // self._num_heads
        return x.reshape((b, l, h, d)).transpose((0, 2, 1, 3))

    # NOTE (round 5): a "fused" split variant — one (B,L,3,H,D) ->
    # (3,B,H,L,D) transpose + free slices instead of split + 3 head
    # transposes — measured SLOWER end-to-end (BERT-base 266.9 vs 272.6
    # samples/s on v5e): XLA already overlaps the three small relayouts
    # better than one big one. Kept as a note, not code.

    def _merge_heads(self, F, x):
        b, h, l, d = x.shape
        return x.transpose((0, 2, 1, 3)).reshape((b, l, h * d))

    def hybrid_forward(self, F, query, memory=None, mask=None):
        if self._cross:
            if memory is None:
                memory = query
            q = self.q_proj(query)
            kv = self.kv_proj(memory)
            k, v = F.split(kv, num_outputs=2, axis=-1)
        else:
            qkv = self.qkv_proj(query)
            q, k, v = F.split(qkv, num_outputs=3, axis=-1)
        q = self._split_heads(F, q)
        k = self._split_heads(F, k)
        v = self._split_heads(F, v)
        if mask is not None:
            out = F._contrib_sdp_attention(q, k, v, mask, causal=self._causal,
                                           dropout=self._attn_dropout)
        else:
            out = F._contrib_sdp_attention(q, k, v, causal=self._causal,
                                           ring_axis=self._ring_axis,
                                           dropout=self._attn_dropout)
        out = self._merge_heads(F, out)
        out = self.out_proj(out)
        if self.dropout is not None:
            out = self.dropout(out)
        return out
