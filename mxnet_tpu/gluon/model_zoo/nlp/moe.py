"""Mixture-of-Experts layer with expert parallelism (capability row:
GShard/Switch-style sparse FFN; no upstream-MXNet counterpart — this is
the `ep` axis of the parallelism zoo).

TPU-native formulation: dense dispatch/combine einsums over an
``(experts, capacity)`` layout — the GShard construction — so the layer
is pure tensor algebra inside the jitted step and GSPMD inserts the
token all-to-alls when expert weights are sharded over the ``ep`` mesh
axis (``moe_sharding_rules``). No data-dependent shapes: dropped tokens
(capacity overflow) contribute zero, exactly like the reference GShard
capacity semantics.
"""
from __future__ import annotations

import math

from ...block import HybridBlock
from ... import nn

__all__ = ["MoEMLP", "moe_sharding_rules"]


class MoEMLP(HybridBlock):
    """Top-k routed expert FFN (drop-in for a dense MLP on (B, L, U)).

    Parameters: ``num_experts`` experts, each a SwiGLU MLP with
    ``hidden_size`` units; ``top_k`` experts per token; ``capacity_factor``
    bounds per-expert load (tokens beyond capacity are dropped — their
    combine weight is zero, the GShard contract).
    """

    def __init__(self, units, hidden_size, num_experts, top_k=2,
                 capacity_factor=1.25, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if top_k > num_experts:
            raise ValueError("top_k cannot exceed num_experts")
        self._units = units
        self._hidden = hidden_size
        self._e = num_experts
        self._k = top_k
        self._cf = float(capacity_factor)
        with self.name_scope():
            self.router = nn.Dense(num_experts, flatten=False,
                                   use_bias=False, prefix="router_")
            # per-expert weights as stacked tensors: ONE einsum per matmul
            # across all experts (the MXU-friendly layout; 'ep' shards
            # the leading expert dim)
            self.gate_up_weight = self.params.get(
                "gate_up_weight", shape=(num_experts, units,
                                         2 * hidden_size),
                init="xavier")
            self.down_weight = self.params.get(
                "down_weight", shape=(num_experts, hidden_size, units),
                init="xavier")

    def hybrid_forward(self, F, x, gate_up_weight, down_weight):
        b, l, u = x.shape
        n = b * l
        tokens = x.reshape((n, u))
        logits = self.router(tokens)                      # (N, E)
        probs = F.softmax(logits, axis=-1)

        capacity = max(1, int(math.ceil(n * self._cf * self._k / self._e)))
        out = F._contrib_moe_dispatch_combine(
            tokens, probs, gate_up_weight, down_weight,
            top_k=self._k, capacity=capacity)
        return out.reshape((b, l, u))


def moe_sharding_rules(ep_axis="ep", extra=()):
    """Expert-parallel layout: expert-stacked weights shard on the expert
    dim; compose with tensor/data rules via ``extra``."""
    from ....parallel import ShardingRules
    from jax.sharding import PartitionSpec as P

    return ShardingRules(list(extra) + [
        (r"(gate_up|down)_weight$", P(ep_axis, None, None)),
    ])
