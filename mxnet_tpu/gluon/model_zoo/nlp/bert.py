"""BERT — the GluonNLP pretraining/finetune capability.

Reference capability: gluonnlp `bert_12_768_12` / `bert_24_1024_16`
(BERTModel + BERTEncoder over MXNet fused attention,
src/operator/contrib/transformer.cc). TPU-native re-design: post-LN encoder
cells over `_contrib_sdp_attention` (f32 softmax, Pallas flash path),
learned position embeddings added in-graph, bf16-friendly throughout. The
masked-LM decoder ties the word embedding, and the pooler/NSP heads match
the reference model surface so finetune scripts port 1:1.
"""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn
from .transformer import TransformerEncoderCell

__all__ = ["BERTEncoder", "BERTModel", "BERTForPretrainFused",
           "bert_12_768_12", "bert_24_1024_16",
           "bert_sharding_rules"]


class BERTEncoder(HybridBlock):
    """Stack of post-LN transformer cells with GELU FFN."""

    def __init__(self, num_layers=12, units=768, hidden_size=3072,
                 num_heads=12, dropout=0.1, attn_dropout=0.0,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.cells = nn.HybridSequential(prefix="")
            for i in range(num_layers):
                # BERT FFN uses GELU (reference: gluonnlp BERTEncoder);
                # attn_dropout = dropout ON the attention probabilities
                # (gluonnlp BERTEncoder attention_dropout), generated
                # inside the flash kernels
                self.cells.add(TransformerEncoderCell(
                    units, hidden_size, num_heads, dropout=dropout,
                    activation="gelu", attn_dropout=attn_dropout,
                    prefix=f"layer{i}_"))

    def hybrid_forward(self, F, x, mask=None):
        for cell in self.cells._children.values():
            x = cell(x, mask) if mask is not None else cell(x)
        return x


class BERTModel(HybridBlock):
    """word + token-type + position embeddings -> encoder -> heads.

    Outputs (matching the reference surface):
      sequence_output (B, L, U); pooled_output (B, U);
      and when ``use_decoder`` the masked-LM logits (B, L, vocab).
    """

    def __init__(self, vocab_size=30522, token_type_vocab_size=2,
                 max_length=512, num_layers=12, units=768, hidden_size=3072,
                 num_heads=12, dropout=0.1, attn_dropout=0.0,
                 use_pooler=True,
                 use_classifier=True, use_decoder=True,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        self._use_pooler = use_pooler
        self._use_classifier = use_classifier
        self._use_decoder = use_decoder
        with self.name_scope():
            self.word_embed = nn.Embedding(vocab_size, units,
                                           prefix="word_embed_")
            self.token_type_embed = nn.Embedding(token_type_vocab_size, units,
                                                 prefix="token_type_embed_")
            self.position_embed = nn.Embedding(max_length, units,
                                               prefix="position_embed_")
            self.embed_ln = nn.LayerNorm(prefix="embed_ln_")
            self.embed_dropout = nn.Dropout(dropout) if dropout else None
            self.encoder = BERTEncoder(num_layers, units, hidden_size,
                                       num_heads, dropout,
                                       attn_dropout=attn_dropout,
                                       prefix="enc_")
            if use_pooler:
                self.pooler = nn.Dense(units, flatten=False, activation="tanh",
                                       prefix="pooler_")
            if use_classifier:
                self.classifier = nn.Dense(2, flatten=False,
                                           prefix="classifier_")
            if use_decoder:
                # masked-LM head: transform + tied-embedding output matmul
                self.decoder_transform = nn.Dense(
                    units, flatten=False, activation="gelu",
                    prefix="decoder_transform_")
                self.decoder_ln = nn.LayerNorm(prefix="decoder_ln_")
                self.decoder = nn.Dense(
                    vocab_size, flatten=False,
                    params=self.word_embed.params, prefix="word_embed_")

    def hybrid_forward(self, F, token_ids, token_types=None, valid_mask=None):
        l = token_ids.shape[1]
        positions = F.arange(0, l, dtype="float32")
        x = self.word_embed(token_ids)
        if token_types is not None:
            x = x + self.token_type_embed(token_types)
        x = x + self.position_embed(positions).reshape((1, l, self._units))
        x = self.embed_ln(x)
        if self.embed_dropout is not None:
            x = self.embed_dropout(x)
        attn_mask = None
        if valid_mask is not None:
            # (B, L) 1/0 -> (B, 1, 1, L): every query may attend valid keys
            attn_mask = valid_mask.reshape(
                (valid_mask.shape[0], 1, 1, valid_mask.shape[1]))
        seq = self.encoder(x, attn_mask)
        outs = [seq]
        pooled = None
        if self._use_pooler:
            pooled = self.pooler(seq[:, 0:1, :].reshape((-1, self._units)))
            outs.append(pooled)
        if self._use_classifier and pooled is not None:
            outs.append(self.classifier(pooled))
        if self._use_decoder:
            h = self.decoder_ln(self.decoder_transform(seq))
            outs.append(self.decoder(h))
        return tuple(outs) if len(outs) > 1 else outs[0]


def bert_sharding_rules(tp_axis="tp"):
    """Megatron TP layout for BERT (same rule shapes as the transformer)."""
    from .transformer import transformer_sharding_rules

    return transformer_sharding_rules(tp_axis)


def bert_12_768_12(**kwargs):
    """BERT-base (reference capability: gluonnlp bert_12_768_12)."""
    cfg = dict(num_layers=12, units=768, hidden_size=3072, num_heads=12)
    cfg.update(kwargs)
    return BERTModel(**cfg)


def bert_24_1024_16(**kwargs):
    """BERT-large (reference capability: gluonnlp bert_24_1024_16)."""
    cfg = dict(num_layers=24, units=1024, hidden_size=4096, num_heads=16)
    cfg.update(kwargs)
    return BERTModel(**cfg)


class BERTForPretrainFused(HybridBlock):
    """BERT masked-LM pretraining with the FUSED projection+CE head.

    Identical parameters and math to ``BERTModel(use_decoder=True)`` + a
    sparse softmax CE over the (B, L, vocab) logits — but the logits are
    never materialized: ``_contrib_softmax_ce_head`` scans vocab chunks
    with an online logsumexp (the SoftmaxOutput lineage taken one step
    further; see ops/fused_loss.py). On BERT-base the logits tensor and
    its relayout copies were ~6 GB of HBM traffic per step (PERF.md
    round 3).

    ``forward(token_ids, mlm_labels) -> (B, L)`` per-position loss; use
    with ``TrainStep(net, loss_fn=mean, loss_only=True)`` passing the
    labels as a second DATA input.

    Parameter-name note: the head lives at THIS block's scope
    (``decoder_transform_*`` / ``decoder_bias``), while
    ``BERTModel(use_decoder=True)`` scopes its head inside the backbone
    — checkpoints move between the two pretraining paths via name-mapped
    ``load_parameters``, not byte-identical files.
    """

    def __init__(self, vocab_size=30522, token_type_vocab_size=2,
                 max_length=512, num_layers=12, units=768, hidden_size=3072,
                 num_heads=12, dropout=0.1, attn_dropout=0.0, chunk=5120,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        self._chunk = chunk
        with self.name_scope():
            self.bert = BERTModel(
                vocab_size=vocab_size,
                token_type_vocab_size=token_type_vocab_size,
                max_length=max_length, num_layers=num_layers, units=units,
                hidden_size=hidden_size, num_heads=num_heads,
                dropout=dropout, attn_dropout=attn_dropout,
                use_pooler=False, use_classifier=False,
                use_decoder=False, prefix="bert_")
            self.decoder_transform = nn.Dense(
                units, flatten=False, activation="gelu",
                prefix="decoder_transform_")
            self.decoder_ln = nn.LayerNorm(prefix="decoder_ln_")
            # output projection stays TIED to the word embedding; its bias
            # is this block's own parameter (reference decoder layout)
            self.vocab_bias = self.params.get(
                "decoder_bias", shape=(vocab_size,), init="zeros")

    def hybrid_forward(self, F, token_ids, mlm_labels, vocab_bias):
        seq = self.bert(token_ids)
        h = self.decoder_ln(self.decoder_transform(seq))
        # the tied projection weight is the backbone's embedding table;
        # under a TrainStep trace p.data() resolves to the traced value,
        # so gradients flow to the shared parameter from BOTH uses
        w = self.bert.word_embed.weight.data(token_ids.context)
        return F._contrib_softmax_ce_head(h, w, vocab_bias, mlm_labels,
                                          chunk=self._chunk)
