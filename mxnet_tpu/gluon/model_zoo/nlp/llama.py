"""Llama-style decoder-only LM — the stretch config (BASELINE.json config[4]).

No reference counterpart (the reference pre-dates Llama; SURVEY.md §5.7
flags long-context as a new capability). TPU-first design choices:
* RMSNorm in f32, output in compute dtype;
* RoPE computed in-graph from positions (no host tables, no recompiles
  across sequence lengths within a bucket);
* grouped-query attention (n_kv_heads < n_heads) through the same
  `_contrib_sdp_attention` seam (kv heads broadcast to q heads);
* SwiGLU FFN as two fused matmuls (gate+up projected together);
* Megatron TP rules + sequence-axis sharding hooks for ring attention.
"""
from __future__ import annotations

import math

from ...block import HybridBlock
from ... import nn
from ...parameter import Parameter

__all__ = ["RMSNorm", "LlamaAttention", "LlamaMLP", "LlamaBlock",
           "LlamaModel", "LlamaDecodeEngine", "llama_tiny", "llama_3_8b",
           "llama_sharding_rules", "LlamaModelPP", "llama_tiny_pp",
           "llama_pp_sharding_rules"]


class RMSNorm(HybridBlock):
    """f32-statistics RMSNorm. Under ``MXNET_PALLAS_FUSED=1`` the
    ``_contrib_rms_norm`` op routes to the fused Pallas kernel
    (pallas_kernels/fused_layers.py, RMS mode) on TPU — every Llama
    block adopts the fused layer path through this seam."""

    def __init__(self, units, eps=1e-6, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._eps = eps
        with self.name_scope():
            self.weight = self.params.get("weight", shape=(units,),
                                          init="ones")

    def hybrid_forward(self, F, x, weight):
        return F._contrib_rms_norm(x, weight, eps=self._eps)


class LlamaAttention(HybridBlock):
    def __init__(self, units, num_heads, num_kv_heads=None, rope_theta=10000.0,
                 ring_axis=None, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        num_kv_heads = num_kv_heads or num_heads
        if num_heads % num_kv_heads:
            raise ValueError("num_heads must be divisible by num_kv_heads")
        self._units = units
        self._h = num_heads
        self._kv = num_kv_heads
        self._d = units // num_heads
        self._theta = rope_theta
        self._ring_axis = ring_axis  # sequence-parallel ring attention
        with self.name_scope():
            self.q_proj = nn.Dense(units, flatten=False, use_bias=False,
                                   prefix="q_")
            self.kv_proj = nn.Dense(2 * self._kv * self._d, flatten=False,
                                    use_bias=False, prefix="kv_")
            self.out_proj = nn.Dense(units, flatten=False, use_bias=False,
                                     prefix="out_")

    def hybrid_forward(self, F, x):
        b, l = x.shape[0], x.shape[1]
        q = self.q_proj(x).reshape((b, l, self._h, self._d))
        kv = self.kv_proj(x).reshape((b, l, 2 * self._kv, self._d))
        k, v = F.split(kv, num_outputs=2, axis=2)
        q = F._contrib_rope(q, theta=self._theta)
        k = F._contrib_rope(k, theta=self._theta)
        # (B, L, H, D) -> (B, H, L, D); kv heads repeat up to q heads (GQA)
        q = q.transpose((0, 2, 1, 3))
        k = k.transpose((0, 2, 1, 3))
        v = v.transpose((0, 2, 1, 3))
        if self._kv != self._h:
            rep = self._h // self._kv
            k = F.repeat(k, repeats=rep, axis=1)
            v = F.repeat(v, repeats=rep, axis=1)
        out = F._contrib_sdp_attention(q, k, v, causal=True,
                                       ring_axis=self._ring_axis)
        out = out.transpose((0, 2, 1, 3)).reshape((b, l, self._units))
        return self.out_proj(out)


class LlamaMLP(HybridBlock):
    """SwiGLU: gate and up projected in ONE matmul, then silu(gate)*up."""

    def __init__(self, units, hidden_size, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden = hidden_size
        with self.name_scope():
            self.gate_up = nn.Dense(2 * hidden_size, flatten=False,
                                    use_bias=False, prefix="gateup_")
            self.down = nn.Dense(units, flatten=False, use_bias=False,
                                 prefix="down_")

    def hybrid_forward(self, F, x):
        gu = self.gate_up(x)
        gate, up = F.split(gu, num_outputs=2, axis=-1)
        return self.down(F.Activation(gate, act_type="silu") * up)


class LlamaBlock(HybridBlock):
    def __init__(self, units, hidden_size, num_heads, num_kv_heads=None,
                 rope_theta=10000.0, eps=1e-6, ring_axis=None, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.attn_norm = RMSNorm(units, eps, prefix="attnnorm_")
            self.attention = LlamaAttention(units, num_heads, num_kv_heads,
                                            rope_theta, ring_axis=ring_axis,
                                            prefix="attn_")
            self.mlp_norm = RMSNorm(units, eps, prefix="mlpnorm_")
            self.mlp = LlamaMLP(units, hidden_size, prefix="mlp_")

    def hybrid_forward(self, F, x):
        x = x + self.attention(self.attn_norm(x))
        return x + self.mlp(self.mlp_norm(x))


def _best_ce_chunk(vocab, target=8192):
    """Largest divisor of ``vocab`` <= target (the fused-CE tile size that
    keeps the bias-free path reachable — e.g. 8016 for Llama-3's 128256).
    A vocab <= target is its own (single) chunk. Only when every divisor
    is degenerate (< target/4, e.g. a large near-prime vocab) fall back to
    ``target`` and accept the padded path."""
    if vocab <= target:
        return vocab
    for c in range(target, 0, -1):
        if vocab % c == 0:  # c=1 always divides, so this always returns
            return c if c >= target // 4 else target


class LlamaModel(HybridBlock):
    """Decoder-only causal LM; returns (B, L, vocab) logits."""

    def __init__(self, vocab_size=128256, num_layers=32, units=4096,
                 hidden_size=14336, num_heads=32, num_kv_heads=8,
                 rope_theta=500000.0, eps=1e-5, tie_weights=False,
                 ring_axis=None, remat=False, fused_ce=False,
                 ce_chunk=None, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        # architecture record for the paged decode engine (serving):
        # everything the pure decode forward needs that the blocks
        # otherwise keep in closed-over layer attributes
        num_kv = num_kv_heads or num_heads
        self._decode_cfg = {
            "vocab_size": int(vocab_size), "num_layers": int(num_layers),
            "units": int(units), "num_heads": int(num_heads),
            "num_kv_heads": int(num_kv),
            "head_dim": int(units // num_heads),
            "rope_theta": float(rope_theta), "eps": float(eps),
        }
        # per-block gradient rematerialization (jax.checkpoint) inside
        # compiled train steps — pretrain-scale memory policy. ``remat``
        # may be a bool (True = save-nothing "full" policy) or a policy
        # name accepted by gluon.block.remat_call ("full" | "dots");
        # normalized here to policy-name-or-None
        self._remat = remat if isinstance(remat, str) else \
            ("full" if remat else None)
        # fused projection+CE head (ops/fused_loss.py): forward takes
        # (tokens, labels) and returns per-token loss; the (B, L, vocab)
        # logits never materialize — at pretrain vocab sizes they are
        # the largest intermediate of the step
        self._fused_ce = bool(fused_ce)
        # chunk must DIVIDE vocab for the bias-free fast path of
        # softmax_ce_head (a non-divisor falls back to padding + a
        # synthetic zero bias whose vocab-sized cotangent the fast path
        # exists to avoid — round-3 advisor finding). Default: largest
        # divisor of vocab <= 8192, e.g. 8016 for the Llama-3 128256.
        if ce_chunk and vocab_size % int(ce_chunk):
            # warn, don't raise: the default itself may legitimately pick
            # a non-divisor for near-prime vocabs (padded fallback is the
            # only option there) — but an accidental non-divisor when good
            # divisors exist deserves a loud signal
            import warnings

            best = _best_ce_chunk(vocab_size)
            warnings.warn(
                f"ce_chunk={ce_chunk} does not divide vocab_size="
                f"{vocab_size}: the fused CE head takes the padded "
                "fallback with a vocab-sized synthetic-bias cotangent"
                + (f"; a dividing chunk exists ({best})"
                   if vocab_size % best == 0 else ""),
                stacklevel=3)
        self._ce_chunk = int(ce_chunk) if ce_chunk else \
            _best_ce_chunk(vocab_size)
        with self.name_scope():
            self.embed = nn.Embedding(vocab_size, units, prefix="embed_")
            self.blocks = []
            for i in range(num_layers):
                blk = LlamaBlock(units, hidden_size, num_heads, num_kv_heads,
                                 rope_theta, eps, ring_axis=ring_axis,
                                 prefix=f"layer{i}_")
                self.blocks.append(blk)
                self.register_child(blk, f"layer{i}")
            self.norm = RMSNorm(units, eps, prefix="norm_")
            # explicit in_units: in fused-CE mode the Dense's own
            # forward never runs, so the weight must not be deferred
            if tie_weights:
                self.lm_head = nn.Dense(vocab_size, in_units=units,
                                        flatten=False, use_bias=False,
                                        params=self.embed.params,
                                        prefix="embed_")
            else:
                self.lm_head = nn.Dense(vocab_size, in_units=units,
                                        flatten=False, use_bias=False,
                                        prefix="lm_head_")

    def hybrid_forward(self, F, tokens, labels=None):
        from ...block import remat_call

        x = self.embed(tokens)
        for blk in self.blocks:
            x = remat_call(blk, x, policy=self._remat) if self._remat \
                else blk(x)
        h = self.norm(x)
        if self._fused_ce:
            if labels is None:
                raise ValueError(
                    "LlamaModel(fused_ce=True) takes (tokens, labels) and "
                    "returns the per-token loss")
            w = self.lm_head.weight.data(tokens.context)
            return F._contrib_softmax_ce_head(h, w, None, labels,
                                              chunk=self._ce_chunk)
        return self.lm_head(h)

    def decode_engine(self, pool, dtype: str = "float32"
                      ) -> "LlamaDecodeEngine":
        """Build the paged-KV decode engine for serving (the seam
        ``serving.Server`` probes for to enable ``submit_generate``).
        ``pool``: a :class:`mxnet_tpu.serving.kvcache.PagePool`."""
        from ...parameter import DeferredInitializationError
        try:
            return LlamaDecodeEngine(self, pool, dtype=dtype)
        except DeferredInitializationError:
            from .... import nd
            self(nd.zeros((1, 2), dtype="int32"))  # materialize shapes
            return LlamaDecodeEngine(self, pool, dtype=dtype)


class LlamaModelPP(HybridBlock):
    """Llama with the layer trunk pipelined over the mesh's ``pp`` axis.

    ``num_layers = n_stages * layers_per_stage``; the trunk is ONE
    :class:`~mxnet_tpu.parallel.Pipelined` block whose stage-stacked
    parameters shard over ``pp`` while embed/norm/head stay GSPMD-managed
    (replicated over ``pp``, shardable over ``tp``/``dp`` as usual).
    Off-mesh it computes the identical function sequentially.
    """

    def __init__(self, vocab_size=256, n_stages=4, layers_per_stage=1,
                 units=64, hidden_size=128, num_heads=4, num_kv_heads=None,
                 rope_theta=10000.0, eps=1e-6, n_microbatches=None,
                 remat=False, ring_axis=None, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        from ....parallel.pipeline import Pipelined

        if isinstance(remat, str):
            # Pipelined's remat is jax.checkpoint over the stage scan with
            # the default policy only; a policy string would be silently
            # bool()-coerced to full remat — reject instead of lying
            raise ValueError(
                "LlamaModelPP supports remat=True/False only (the "
                "pipelined trunk's checkpoint has no policy plumbing); "
                f"got remat={remat!r}")
        self._units = units
        with self.name_scope():
            self.embed = nn.Embedding(vocab_size, units, prefix="embed_")
            self.trunk = Pipelined(
                lambda: LlamaBlock(units, hidden_size, num_heads,
                                   num_kv_heads, rope_theta, eps,
                                   ring_axis=ring_axis, prefix="stage_"),
                n_stages=n_stages, layers_per_stage=layers_per_stage,
                n_microbatches=n_microbatches, remat=remat,
                prefix="trunk_")
            self.norm = RMSNorm(units, eps, prefix="norm_")
            self.lm_head = nn.Dense(vocab_size, flatten=False,
                                    use_bias=False, prefix="lm_head_")

    def hybrid_forward(self, F, tokens):
        x = self.embed(tokens)
        x = self.trunk(x)
        return self.lm_head(self.norm(x))


def llama_tiny_pp(n_stages=4, **kwargs):
    """Test-sized pipelined config (CI / dry-run)."""
    cfg = dict(vocab_size=256, n_stages=n_stages, layers_per_stage=1,
               units=64, hidden_size=128, num_heads=4, num_kv_heads=2,
               rope_theta=10000.0)
    cfg.update(kwargs)
    return LlamaModelPP(**cfg)


def llama_pp_sharding_rules(pp_axis="pp", tp_axis="tp"):
    """PP stage axis on the stacked trunk params, composed with the
    Megatron TP splits (shifted by the (stage, layer) lead dims) and the
    usual vocab-parallel embed/head."""
    from ....parallel import ShardingRules
    from ....parallel.pipeline import pipeline_sharding_rules
    from jax.sharding import PartitionSpec as P

    rules = ShardingRules([
        (r"(embed|lm_head)_weight$", P(tp_axis, None)),
    ])
    rules.extend(pipeline_sharding_rules(pp_axis, extra=[
        (r"pp_.*(q|kv|gateup)_weight$", (tp_axis,)),
        (r"pp_.*(out|down)_weight$", (None, tp_axis)),
    ]))
    return rules


def llama_sharding_rules(tp_axis="tp"):
    """Megatron TP: q/kv/gate-up column-parallel, out/down row-parallel,
    embedding + lm_head vocab-parallel."""
    from ....parallel import ShardingRules
    from jax.sharding import PartitionSpec as P

    return ShardingRules([
        (r"(q|kv|gateup)_weight$", P(tp_axis, None)),
        (r"(out|down)_weight$", P(None, tp_axis)),
        (r"(embed|lm_head)_weight$", P(tp_axis, None)),
    ])


# ---------------------------------------------------------------------------
# paged-KV decode engine (serving)
# ---------------------------------------------------------------------------

_DECODE_SITE = "serving_decode"


def _paged_forward(params, tokens, positions, page_table, lengths,
                   k_arena, v_arena, *, cfg, page_size):
    """Pure cache-aware forward: embeds ``tokens`` (B, L) at absolute
    ``positions`` (B, L), scatters each layer's K/V into the paged
    arenas, attends through the page table, and returns the logits of
    the LAST valid input position per row plus the updated arenas.

    One function serves both phases — prefill is (B, len-bucket),
    decode is (B, 1) — so both compile through the same cache site and
    the decode step is ONE executable per batch bucket. Positions at or
    beyond a row's ``lengths`` (bucket padding, whole-row batch
    padding) scatter into the reserved scratch page 0 and are masked
    out of every attention read — bit-transparent padding, extended to
    the cache.
    """
    import jax
    import jax.numpy as jnp

    from ....ops.attention import paged_attention, rms_norm, rope_at

    embed_w, layer_params, norm_w, head_w = params
    n_heads = cfg["num_heads"]
    n_kv = cfg["num_kv_heads"]
    d = cfg["head_dim"]
    theta = cfg["rope_theta"]
    eps = cfg["eps"]
    ps = int(page_size)
    b, l = tokens.shape
    w_pages = page_table.shape[1]

    x = jnp.take(embed_w, tokens, axis=0)               # (B, L, U)
    real = positions < lengths[:, None]
    page_of = jnp.clip(positions // ps, 0, w_pages - 1)
    page_ids = jnp.take_along_axis(page_table, page_of, axis=1)
    slot = jnp.where(real, page_ids * ps + positions % ps,
                     positions % ps)                    # padding -> scratch
    slot_flat = slot.reshape(-1)

    for li, (anw, qw, kvw, ow, mnw, guw, dw) in enumerate(layer_params):
        h = rms_norm(x, anw, eps=eps)
        q = (h @ qw.T).reshape(b, l, n_heads, d)
        kv = (h @ kvw.T).reshape(b, l, 2 * n_kv, d)
        k, v = kv[:, :, :n_kv], kv[:, :, n_kv:]
        q = rope_at(q, positions, theta=theta)
        k = rope_at(k, positions, theta=theta)
        k_arena = k_arena.at[li, slot_flat].set(k.reshape(b * l, n_kv, d))
        v_arena = v_arena.at[li, slot_flat].set(v.reshape(b * l, n_kv, d))
        att = paged_attention(q.transpose(0, 2, 1, 3), k_arena[li],
                              v_arena[li], page_table, lengths,
                              q_positions=positions, page_size=ps)
        att = att.transpose(0, 2, 1, 3).reshape(b, l, n_heads * d)
        x = x + att @ ow.T
        hm = rms_norm(x, mnw, eps=eps)
        gate, up = jnp.split(hm @ guw.T, 2, axis=-1)
        x = x + (jax.nn.silu(gate) * up) @ dw.T

    hfin = rms_norm(x, norm_w, eps=eps)
    # logits of the last REAL input row: axis index lengths-1-positions[:,0]
    # (prefill: lengths-1; decode L=1: always 0). Whatever L, this is a
    # (B, U) @ (U, V) contraction — the same lowering for both phases.
    last = jnp.clip(lengths - 1 - positions[:, 0], 0, l - 1)
    h_last = jnp.take_along_axis(
        hfin, last[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    return h_last @ head_w.T, k_arena, v_arena


class LlamaDecodeEngine:
    """Cache-aware generation engine over one :class:`LlamaModel`.

    Owns the per-replica K/V arenas (pages allocated from ``pool``) and
    dispatches :func:`_paged_forward` through the compiler service's
    ``serving_decode`` cache site: one executable per (batch-bucket,
    len-bucket) prefill signature, ONE ``(batch, 1)`` executable per
    batch bucket for every decode step — zero steady-state retraces
    (``mxnet_jit_cache_total{cache="serving_decode"}`` is the marker).

    Not thread-safe by design: exactly one scheduler thread drives it
    (the :class:`~mxnet_tpu.serving.server.Server` contract).
    """

    def __init__(self, model, pool, dtype: str = "float32"):
        from ....serving.kvcache import make_kv_arena

        self.cfg = dict(model._decode_cfg)
        self.pool = pool
        self.page_size = pool.page_size
        self.dtype = dtype
        self._ident = ("llama", tuple(sorted(self.cfg.items())), dtype)
        self.k_arena, self.v_arena = make_kv_arena(
            self.cfg["num_layers"], pool, self.cfg["num_kv_heads"],
            self.cfg["head_dim"], dtype)
        self.refresh_params(model)

    def refresh_params(self, model) -> None:
        """(Re)extract the weight arrays — called at build and after a
        model swap once no in-flight generate still needs the old
        weights (a request's whole completion runs on ONE version)."""
        import jax.numpy as jnp

        def w(p):
            return jnp.asarray(p.data().data, dtype=self.dtype)

        self._params = (
            w(model.embed.weight),
            tuple((w(blk.attn_norm.weight), w(blk.attention.q_proj.weight),
                   w(blk.attention.kv_proj.weight),
                   w(blk.attention.out_proj.weight),
                   w(blk.mlp_norm.weight), w(blk.mlp.gate_up.weight),
                   w(blk.mlp.down.weight))
                  for blk in model.blocks),
            w(model.norm.weight), w(model.lm_head.weight))

    # -- dispatch ------------------------------------------------------
    def _fn(self, b, l, w_pages):
        import functools

        import jax

        from ....compiler import service as _csvc
        from ....compiler import signature

        cache = _csvc.shared_cache(_DECODE_SITE)
        key = signature(
            _DECODE_SITE, self._ident,
            avals=((b, l), (b, w_pages), self.dtype),
            attrs=(self.page_size,), platform=jax.default_backend())
        fn = cache.lookup(key)
        if fn is not cache.MISS:
            return fn
        # CPU XLA does not honor donation (it would warn per call);
        # elsewhere the arenas are donated so the scatter updates alias
        jit_kw = {} if jax.default_backend() == "cpu" \
            else {"donate_argnums": (5, 6)}
        fn = jax.jit(functools.partial(_paged_forward, cfg=self.cfg,
                                       page_size=self.page_size), **jit_kw)
        cache.insert(key, fn)
        return fn

    def forward(self, tokens, positions, page_table, lengths):
        """Run one cache-aware forward; numpy in, numpy logits (B, vocab)
        out; the arenas advance in place (functionally)."""
        import jax.numpy as jnp
        import numpy as _np

        tokens = _np.asarray(tokens, dtype=_np.int32)
        fn = self._fn(tokens.shape[0], tokens.shape[1],
                      _np.shape(page_table)[1])
        logits, self.k_arena, self.v_arena = fn(
            self._params, jnp.asarray(tokens),
            jnp.asarray(_np.asarray(positions, dtype=_np.int32)),
            jnp.asarray(_np.asarray(page_table, dtype=_np.int32)),
            jnp.asarray(_np.asarray(lengths, dtype=_np.int32)),
            self.k_arena, self.v_arena)
        return _np.asarray(logits)

    def prefill(self, tokens, lengths, page_table):
        """Prefill (B, len-bucket) prompts; ``lengths`` are the real
        prompt lengths. Returns the next-token logits per row."""
        import numpy as _np

        b, l = _np.shape(tokens)
        positions = _np.broadcast_to(_np.arange(l, dtype=_np.int32), (b, l))
        return self.forward(tokens, positions, page_table, lengths)

    def decode_step(self, tokens, lengths, page_table):
        """One continuous-batching decode step: ``tokens`` (B,) are the
        rows' newest tokens, already counted in ``lengths``. ONE
        (B, 1)-shaped executable regardless of how deep each row is."""
        import numpy as _np

        tokens = _np.asarray(tokens, dtype=_np.int32).reshape(-1, 1)
        positions = (_np.asarray(lengths, dtype=_np.int32) - 1
                     ).reshape(-1, 1)
        return self.forward(tokens, positions, page_table, lengths)

    def apply_defrag(self, moves) -> None:
        """Replay :meth:`PagePool.defrag` page moves onto this engine's
        arenas — called by the serving scheduler between decode steps,
        BEFORE any dispatch reads the renumbered page tables. In a
        multi-tenant server every engine replays the SAME global
        permutation (the pool's accounting is shared), so a page another
        tenant owns moves its (garbage, for this engine) slots too —
        harmless, and it keeps every arena consistent with the one page
        numbering."""
        from ....serving.kvcache import apply_defrag

        self.k_arena = apply_defrag(self.k_arena, moves, self.page_size)
        self.v_arena = apply_defrag(self.v_arena, moves, self.page_size)

    def forward_full(self, tokens):
        """No-cache full-recompute oracle: run the whole (B, L) prefix
        through scratch pages and return the next-token logits. Frees
        its pages before returning — the O(n²) baseline path."""
        import numpy as _np

        tokens = _np.asarray(tokens, dtype=_np.int32)
        b, l = tokens.shape
        owners = [object() for _ in range(b)]
        width = self.pool.pages_for(l)
        table = _np.zeros((b, width), dtype=_np.int32)
        try:
            for i, o in enumerate(owners):
                table[i] = self.pool.alloc(o, l)
            return self.prefill(tokens,
                                _np.full((b,), l, dtype=_np.int32), table)
        finally:
            for o in owners:
                self.pool.free(o)


def llama_tiny(**kwargs):
    """Test-sized config (CI / dry-run)."""
    cfg = dict(vocab_size=256, num_layers=2, units=64, hidden_size=128,
               num_heads=4, num_kv_heads=2, rope_theta=10000.0)
    cfg.update(kwargs)
    return LlamaModel(**cfg)


def llama_3_8b(**kwargs):
    """Llama-3-8B shapes (BASELINE.json stretch config)."""
    cfg = dict(vocab_size=128256, num_layers=32, units=4096,
               hidden_size=14336, num_heads=32, num_kv_heads=8,
               rope_theta=500000.0)
    cfg.update(kwargs)
    return LlamaModel(**cfg)
