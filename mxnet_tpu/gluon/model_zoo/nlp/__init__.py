"""NLP model zoo — GluonNLP-capability models, TPU-first.

Reference capability: the GluonNLP model zoo consumed through the Gluon API
(SURVEY.md §1 L8: "GluonCV / GluonNLP are separate repos consuming the
Gluon API", named in BASELINE.json configs 2-4). Families here:

* Transformer NMT (`get_transformer`, capability: transformer_en_de_512)
* BERT (`bert_12_768_12`, `bert_24_1024_16`)
* Llama-style decoder LM (`llama_3_8b` — stretch config, new capability)
* MoE expert-parallel FFN (`MoEMLP`, GShard-style — the `ep` mesh axis)

Each family ships Megatron-style tensor-parallel ShardingRules
(`*_sharding_rules`) consumed by mxnet_tpu.parallel.TrainStep.
"""
from .attention import MultiHeadAttention
from .transformer import (PositionwiseFFN, TransformerEncoderCell,
                          TransformerDecoderCell, TransformerEncoder,
                          TransformerDecoder, Transformer, get_transformer,
                          transformer_sharding_rules)
from .bert import (BERTEncoder, BERTModel, bert_12_768_12, bert_24_1024_16,
                   bert_sharding_rules)
from .llama import (RMSNorm, LlamaAttention, LlamaMLP, LlamaBlock,
                    LlamaModel, llama_tiny, llama_3_8b,
                    llama_sharding_rules, LlamaModelPP, llama_tiny_pp,
                    llama_pp_sharding_rules)
from .moe import MoEMLP, moe_sharding_rules

_models = {
    "transformer": get_transformer,
    "bert_12_768_12": bert_12_768_12,
    "bert_24_1024_16": bert_24_1024_16,
    "llama_tiny": llama_tiny,
    "llama_3_8b": llama_3_8b,
    "llama_tiny_pp": llama_tiny_pp,
}


def get_model(name, **kwargs):
    """reference surface: gluonnlp.model.get_model(name)."""
    name = str(name).lower()
    if name not in _models:
        raise ValueError(
            f"unknown nlp model {name!r}; available: {sorted(_models)}")
    return _models[name](**kwargs)
