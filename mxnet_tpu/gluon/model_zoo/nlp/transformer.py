"""Transformer encoder/decoder — the GluonNLP NMT capability.

Reference capability: GluonNLP's `transformer_en_de_512` (scripts/nmt) built
on MXNet's fused attention kernels (src/operator/contrib/transformer.cc).
TPU-native re-design: pre/post-LN cells over the fused
`_contrib_sdp_attention` op, sinusoidal positions computed in-graph (no
host-side tables), everything shaped (batch, seq, units) so the `dp`/`sp`
mesh axes shard dims 0/1 directly.
"""
from __future__ import annotations

import math

import numpy as _np

from ...block import HybridBlock
from ... import nn
from .attention import MultiHeadAttention

__all__ = ["PositionwiseFFN", "TransformerEncoderCell",
           "TransformerDecoderCell", "TransformerEncoder",
           "TransformerDecoder", "Transformer", "get_transformer",
           "transformer_sharding_rules"]


class PositionwiseFFN(HybridBlock):
    """reference capability: gluonnlp PositionwiseFFN (ffn1-act-ffn2)."""

    def __init__(self, units, hidden_size, dropout=0.0, activation="relu",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.ffn1 = nn.Dense(hidden_size, flatten=False,
                                 activation=activation, prefix="ffn1_")
            self.ffn2 = nn.Dense(units, flatten=False, prefix="ffn2_")
            self.dropout = nn.Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x):
        out = self.ffn2(self.ffn1(x))
        if self.dropout is not None:
            out = self.dropout(out)
        return out


class TransformerEncoderCell(HybridBlock):
    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 pre_norm=False, activation="relu", attn_dropout=0.0,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._pre_norm = pre_norm
        self._drop_rate = float(dropout)
        with self.name_scope():
            self.attention = MultiHeadAttention(units, num_heads,
                                                dropout=dropout,
                                                attn_dropout=attn_dropout,
                                                prefix="attn_")
            self.ffn = PositionwiseFFN(units, hidden_size, dropout=dropout,
                                       activation=activation, prefix="ffn_")
            self.ln1 = nn.LayerNorm(prefix="ln1_")
            self.ln2 = nn.LayerNorm(prefix="ln2_")
            self.dropout = nn.Dropout(dropout) if dropout else None

    def _fused_add_norm(self, F, h, residual, ln, dropout=0.0):
        """``LN(dropout(h) + residual)`` through the fused op (one
        Pallas VMEM pass when gated; eager composition otherwise). The
        LayerNorm child keeps owning gamma/beta — parameter names and
        checkpoints are unchanged — but its forward is bypassed, so a
        deferred shape is settled here first."""
        if ln.gamma._data is None:
            ln._infer_param_shapes(h)
        ctx = h.context
        return F._contrib_fused_layer_norm(
            h, ln.gamma.data(ctx), ln.beta.data(ctx), residual,
            eps=ln._epsilon, dropout=dropout)

    def hybrid_forward(self, F, x, mask=None):
        from ....pallas_kernels.fused_layers import fused_layers_enabled

        if self._pre_norm:
            h = self.attention(self.ln1(x), None, mask) if mask is not None \
                else self.attention(self.ln1(x))
            x = x + (self.dropout(h) if self.dropout else h)
            h = self.ffn(self.ln2(x))
            return x + h
        h = self.attention(x, None, mask) if mask is not None \
            else self.attention(x)
        if fused_layers_enabled():
            # post-LN add+norm pairs collapse into the fused op — the
            # PERF.md residue buckets this PR targets (epilogue re-reads,
            # dropout mask traffic, the LN sweep) in one kernel
            x = self._fused_add_norm(F, h, x, self.ln1,
                                     dropout=self._drop_rate)
            h = self.ffn(x)
            return self._fused_add_norm(F, h, x, self.ln2)
        x = self.ln1(x + (self.dropout(h) if self.dropout else h))
        h = self.ffn(x)
        return self.ln2(x + h)


class TransformerDecoderCell(HybridBlock):
    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.self_attention = MultiHeadAttention(
                units, num_heads, dropout=dropout, causal=True,
                prefix="selfattn_")
            self.cross_attention = MultiHeadAttention(
                units, num_heads, dropout=dropout, cross=True,
                prefix="crossattn_")
            self.ffn = PositionwiseFFN(units, hidden_size, dropout=dropout,
                                       prefix="ffn_")
            self.ln1 = nn.LayerNorm(prefix="ln1_")
            self.ln2 = nn.LayerNorm(prefix="ln2_")
            self.ln3 = nn.LayerNorm(prefix="ln3_")

    def hybrid_forward(self, F, x, memory, mem_mask=None):
        h = self.self_attention(x)
        x = self.ln1(x + h)
        h = self.cross_attention(x, memory, mem_mask) if mem_mask is not None \
            else self.cross_attention(x, memory)
        x = self.ln2(x + h)
        return self.ln3(x + self.ffn(x))


def _sinusoid_table(length, units):
    pos = _np.arange(length)[:, None]
    dim = _np.arange(units)[None, :]
    angle = pos / _np.power(10000, 2 * (dim // 2) / units)
    table = _np.where(dim % 2 == 0, _np.sin(angle), _np.cos(angle))
    return table.astype("float32")


class _PositionalEncoding(HybridBlock):
    """Sinusoidal position table added to embeddings (a Constant param so it
    rides inside the compiled graph; reference capability: gluonnlp
    position_weight)."""

    def __init__(self, max_length, units, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        with self.name_scope():
            self.pos_weight = self.params.get_constant(
                "pos_weight", _sinusoid_table(max_length, units))

    def hybrid_forward(self, F, x, pos_weight):
        l = x.shape[1]
        return x * math.sqrt(self._units) + \
            pos_weight[:l].reshape((1, l, self._units))


class TransformerEncoder(HybridBlock):
    def __init__(self, num_layers=6, units=512, hidden_size=2048,
                 num_heads=8, dropout=0.1, max_length=512,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.pos = _PositionalEncoding(max_length, units, prefix="pos_")
            self.dropout = nn.Dropout(dropout) if dropout else None
            self.cells = nn.HybridSequential(prefix="")
            for i in range(num_layers):
                self.cells.add(TransformerEncoderCell(
                    units, hidden_size, num_heads, dropout=dropout,
                    prefix=f"layer{i}_"))

    def hybrid_forward(self, F, x, mask=None):
        x = self.pos(x)
        if self.dropout is not None:
            x = self.dropout(x)
        for cell in self.cells._children.values():
            x = cell(x, mask) if mask is not None else cell(x)
        return x


class TransformerDecoder(HybridBlock):
    def __init__(self, num_layers=6, units=512, hidden_size=2048,
                 num_heads=8, dropout=0.1, max_length=512,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.pos = _PositionalEncoding(max_length, units, prefix="pos_")
            self.dropout = nn.Dropout(dropout) if dropout else None
            self.cells = []
            for i in range(num_layers):
                cell = TransformerDecoderCell(units, hidden_size, num_heads,
                                              dropout=dropout,
                                              prefix=f"layer{i}_")
                self.cells.append(cell)
                self.register_child(cell, f"layer{i}")

    def hybrid_forward(self, F, x, memory, mem_mask=None):
        x = self.pos(x)
        if self.dropout is not None:
            x = self.dropout(x)
        for cell in self.cells:
            x = cell(x, memory, mem_mask)
        return x


class Transformer(HybridBlock):
    """Full NMT transformer (capability parity: gluonnlp
    transformer_en_de_512). Shared source/target embedding and tied output
    projection (tie_weights)."""

    def __init__(self, src_vocab=32768, tgt_vocab=None, num_layers=6,
                 units=512, hidden_size=2048, num_heads=8, dropout=0.1,
                 max_length=512, shared_embed=True, tie_weights=True,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        tgt_vocab = tgt_vocab or src_vocab
        self._units = units
        with self.name_scope():
            self.src_embed = nn.Embedding(src_vocab, units, prefix="src_embed_")
            if shared_embed and tgt_vocab == src_vocab:
                self.tgt_embed = self.src_embed
            else:
                self.tgt_embed = nn.Embedding(tgt_vocab, units,
                                              prefix="tgt_embed_")
                self.register_child(self.tgt_embed, "tgt_embed")
            self.encoder = TransformerEncoder(
                num_layers, units, hidden_size, num_heads, dropout,
                max_length, prefix="enc_")
            self.decoder = TransformerDecoder(
                num_layers, units, hidden_size, num_heads, dropout,
                max_length, prefix="dec_")
            if tie_weights:
                self.proj = nn.Dense(tgt_vocab, flatten=False, use_bias=False,
                                     params=self.tgt_embed.params,
                                     prefix="tgt_embed_")
            else:
                self.proj = nn.Dense(tgt_vocab, flatten=False, use_bias=False,
                                     prefix="proj_")

    def hybrid_forward(self, F, src_tokens, tgt_tokens, src_mask=None):
        memory = self.encoder(self.src_embed(src_tokens), src_mask)
        dec = self.decoder(self.tgt_embed(tgt_tokens), memory, src_mask)
        return self.proj(dec)


def transformer_sharding_rules(tp_axis="tp"):
    """Megatron-style tensor-parallel layout for transformer blocks.

    Column-parallel QKV/FFN-in (shard output features = weight dim 0 in the
    (out, in) MXNet convention), row-parallel out-proj/FFN-out (shard input
    features = dim 1); embeddings sharded on vocab. GSPMD inserts the
    all-reduces after the row-parallel matmuls.
    """
    from ....parallel import ShardingRules
    from jax.sharding import PartitionSpec as P

    return ShardingRules([
        (r"(qkv|q|kv)_weight$", P(tp_axis, None)),
        (r"(qkv|q|kv)_bias$", P(tp_axis)),
        (r"ffn1_weight$", P(tp_axis, None)),
        (r"ffn1_bias$", P(tp_axis)),
        (r"out_weight$", P(None, tp_axis)),
        (r"ffn2_weight$", P(None, tp_axis)),
        (r"embed_weight$", P(tp_axis, None)),
    ])


def get_transformer(**kwargs):
    return Transformer(**kwargs)
