"""Gluon Trainer — the optimizer driver.

Reference: ``python/mxnet/gluon/trainer.py :: Trainer`` — decides
``update_on_kvstore``, `_allreduce_grads` (kv push/pull), `step(batch_size)`,
the `allreduce_grads` + `update` split for gradient clipping, and
save/load_states.

TPU-native notes (SURVEY.md §3.5): with the 'tpu_sync' kvstore the push/pull
pair lowers to one XLA allreduce over the device mesh; with a single device
(the common single-chip path) there is nothing to reduce and step() is just
the optimizer sweep. Multi-context parameter copies follow the reference's
semantics for API parity.
"""
from __future__ import annotations

import logging
import os
import warnings
from typing import Dict, List, Optional

from .. import optimizer as opt
from .. import telemetry
from ..base import MXNetError
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


class Trainer:
    """``check_nonfinite`` (or env ``MXNET_CHECK_NONFINITE=1``): opt-in
    step anomaly guard — a step whose gradients contain NaN/Inf is
    SKIPPED (no optimizer update, no kvstore traffic) and counted
    (``trainer.steps_skipped``, telemetry
    ``mxnet_steps_skipped_total{reason="nonfinite_grad"}``) instead of
    poisoning the weights. When an ``amp.DynamicLossScaler`` is attached
    (``amp.init_trainer``) the scaler owns overflow handling — it skips
    the step AND backs the loss scale off — so the guard defers to it
    rather than double-scanning the gradients."""

    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None, check_nonfinite=None,
                 overlap_comms=None, partition=None,
                 partition_rank=None, partition_world=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise MXNetError(
                "First argument must be a list or dict of Parameters, "
                f"got {type(params)}")
        self._params: List[Parameter] = []
        self._param2idx: Dict[str, int] = {}
        for i, p in enumerate(params):
            if not isinstance(p, Parameter):
                raise MXNetError(f"invalid parameter {p}")
            self._param2idx[p.name] = i
            self._params.append(p)
            p._trainer = self
        self._compression_params = compression_params
        self._scale = 1.0
        if check_nonfinite is None:
            check_nonfinite = os.environ.get(
                "MXNET_CHECK_NONFINITE", "0") == "1"
        self._check_nonfinite = bool(check_nonfinite)
        self.steps_skipped = 0
        optimizer_params = dict(optimizer_params or {})
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_type = kvstore
        self._kvstore = None
        self._kv_initialized = False
        self._update_on_kvstore = update_on_kvstore
        self._contexts = None
        # backward-overlapped comms: dispatch each gradient bucket's
        # pushpull from the autograd grad-ready hook, INSIDE backward()
        if overlap_comms is None:
            overlap_comms = os.environ.get("MXNET_KV_OVERLAP", "0") == "1"
        self._overlap_comms = bool(overlap_comms)
        self._overlap = None
        self.last_overlap_stats = None
        # ZeRO state partitioning (optimizer/zero.py): carve the fused
        # optimizer sweep's flat buckets into per-rank shards —
        # reduce-scatter + shard update + allgather, bit-identical to
        # the replicated path
        if partition is None:
            partition = os.environ.get("MXNET_ZERO_PARTITION") or None
        self._partition = partition
        self._partition_rank = partition_rank
        self._partition_world = partition_world
        self._zero = None

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: p for i, p in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            if optimizer_params:
                raise MXNetError(
                    "optimizer_params must be None when optimizer is an instance")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = None  # per-context Updater list, built lazily

    # ------------------------------------------------------------------
    def _check_contexts(self):
        contexts = None
        for p in self._params:
            ctx = p.list_ctx()
            if contexts is not None and contexts != ctx:
                raise MXNetError(
                    f"All Parameters must be initialized on the same set of "
                    f"contexts, but {p.name} is on {ctx} while others are on "
                    f"{contexts}")
            contexts = ctx
        return contexts or []

    def _init_kvstore(self):
        self._contexts = self._check_contexts()
        if isinstance(self._kvstore_type, str):
            if len(self._contexts) > 1 or self._kvstore_type in (
                    "tpu_sync", "dist_sync", "dist_device_sync", "nccl"):
                from .. import kvstore as kv

                self._kvstore = kv.create(self._kvstore_type)
            else:
                self._kvstore = None
        else:
            self._kvstore = self._kvstore_type
        if self._kvstore is None and self._update_on_kvstore:
            raise MXNetError(
                "update_on_kvstore=True requires a kvstore, but none is "
                "active (single context with kvstore='local'/'device' has "
                "nothing to aggregate); pass kvstore='tpu_sync' or drop "
                "update_on_kvstore")
        if self._kvstore is not None:
            if self._compression_params is not None:
                self._kvstore.set_gradient_compression(
                    self._compression_params)
            if self._update_on_kvstore is None:
                # tpu_sync performs in-graph allreduce; the optimizer always
                # runs worker-side (SURVEY.md §5.8 end-state)
                self._update_on_kvstore = self._kvstore.type not in (
                    "tpu_sync", "local", "device") and len(self._contexts) > 1
            if self._update_on_kvstore:
                self._kvstore.set_optimizer(self._optimizer)
            for i, p in enumerate(self._params):
                if p.grad_req != "null":
                    self._kvstore.init(i, p.data(self._contexts[0]))
        self._updaters = [opt.get_updater(self._optimizer)
                          for _ in self._contexts]
        self._kv_initialized = True
        if self._partition is not None:
            self._init_partition()
        if self._overlap_comms:
            if self._zero is not None:
                # the grad-ready hooks dispatch full-bucket pushpulls;
                # ZeRO members must NOT be pre-reduced (the engine owns
                # their reduce-scatter), so the two modes are exclusive
                warnings.warn(
                    "overlap_comms is disabled under partition="
                    f"{self._partition!r}: the ZeRO engine owns the "
                    "gradient collective for sharded params",
                    stacklevel=2)
            else:
                self._setup_overlap()

    def _init_partition(self):
        from ..optimizer import zero as _zero

        if self._update_on_kvstore:
            raise MXNetError(
                f"partition={self._partition!r} requires a worker-side "
                "optimizer (update_on_kvstore=False) — the sharded "
                "sweep runs on the workers' device mesh")
        if _zero.supported_family(self._optimizer) is None:
            n = sum(1 for p in self._params if p.grad_req != "null")
            telemetry.record_kv_bucket_fallback(_zero.FALLBACK_FAMILY, n)
            warnings.warn(
                f"partition={self._partition!r} ignored: optimizer "
                f"{type(self._optimizer).__name__} is outside the "
                "sharded sweep families (sgd/adam/adamw) — training "
                "continues replicated", stacklevel=2)
            return
        self._zero = _zero.ZeroEngine(
            self, self._partition, rank=self._partition_rank,
            world=self._partition_world)
        self._zero.ensure_ready()

    @property
    def partition(self) -> Optional[str]:
        """The active ZeRO partition mode ('zero1'/'zero2'), or None."""
        return self._zero.mode if self._zero is not None else None

    def partition_manifest(self) -> Optional[dict]:
        """Plan metadata (mode/world/rank/bucket layout, no tensors)
        for checkpoint manifests; None when unpartitioned."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._zero is None:
            return None
        return self._zero.partition_manifest()

    def zero_reconfigure(self, rank, world):
        """Adopt a new (rank, world) partition identity — the elastic
        rejoin hook; see :meth:`ZeroEngine.reconfigure`."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._zero is None:
            raise MXNetError(
                "zero_reconfigure requires an active partition= mode")
        self._zero.reconfigure(rank, world)

    # -- backward-overlapped comms -------------------------------------
    def _setup_overlap(self):
        """Arm the grad-ready hooks (``autograd.watch_grad_ready``) that
        let ``backward()`` dispatch each gradient bucket's ``pushpull``
        the moment its members' grads finalize — the reference engine's
        priority-scheduled push, re-created on the tape. The collective's
        device work then runs under the REST of the backward via JAX
        async dispatch instead of starting after it.

        Engages only when the fused bucketed path would run (worker-side
        optimizer, bucketing on, a store with ``plan_pushpull``) and
        every trainable param has grad_req='write' — 'add' accumulation
        across multiple backwards would reduce a partial gradient.
        Contract: one backward per step (the standard loop); the
        nonfinite guard / AMP scaler must see gradients BEFORE any
        reduce, so those trainers keep the at-step exchange. Note also
        that grad buffers are REDUCED IN PLACE as backward runs: code
        inspecting ``p.grad()`` between ``backward()`` and ``step()``
        (e.g. manual global-norm clipping) would see a mix of reduced
        and still-raw buckets — use ``allreduce_grads()`` +
        ``update()`` with ``overlap_comms=False`` for that pattern."""
        store = self._kvstore
        if (store is None or self._update_on_kvstore
                or not hasattr(store, "plan_pushpull")
                or getattr(store, "_bucket_bytes", 0) <= 0
                or self._check_nonfinite
                or getattr(self, "_amp_loss_scaler", None) is not None):
            return
        if any(p.grad_req == "add" for p in self._params):
            return
        from .. import autograd as ag

        idxs = [i for i, p in enumerate(self._params)
                if p.grad_req != "null"]
        if not idxs:
            return
        watch = {}
        arrays = []
        for i in idxs:
            for a in self._params[i].list_data():
                watch[id(a)] = i
                arrays.append(a)
        if self._overlap is not None:
            ag.unwatch_grad_ready(self._overlap["arrays"])
        self._overlap = {
            "idxs": idxs, "watch": watch, "arrays": arrays,
            "pending": {i: len(self._params[i].list_ctx())
                        for i in idxs},
            "exchange": None, "groups": None, "group_of": {},
            "dispatched": set(), "in_backward": 0, "seq": -1,
        }
        ag.watch_grad_ready(arrays, self._on_grad_ready)

    def _grad_exchange_args(self):
        # ZeRO members are excluded: the engine reduces them itself
        # (psum_scatter inside the sharded sweep) — a kvstore pushpull
        # first would double-reduce
        zero_keys = set(self._zero.eligible_indices()) \
            if self._zero is not None else ()
        keys, grads, priorities = [], [], []
        for i, p in enumerate(self._params):
            if p.grad_req == "null" or i in zero_keys:
                continue
            keys.append(i)
            grads.append(p.list_grad())
            priorities.append(-i)
        return keys, grads, priorities

    def _ensure_overlap_plan(self):
        st = self._overlap
        if st["groups"] is not None:
            return
        keys, grads, priorities = self._grad_exchange_args()
        st["exchange"] = (keys, grads, priorities)
        st["groups"] = self._kvstore.plan_pushpull(keys, grads, priorities)
        for gi, grp in enumerate(st["groups"]):
            for pos in grp:
                st["group_of"][keys[pos]] = gi

    def _on_grad_ready(self, arr):
        """autograd grad-ready hook: fires inside backward() when a
        watched param-copy's gradient buffer is finalized."""
        st = self._overlap
        if st is None:
            return
        if getattr(self, "_amp_loss_scaler", None) is not None:
            return  # scaler owns overflow handling pre-reduce
        from .. import autograd as ag

        seq = ag.backward_sweep_seq()
        if seq != st["seq"]:
            # new backward sweep: if the previous one raised mid-sweep
            # (so step()'s flush/reset never ran), the stale pending/
            # dispatched tracking would silently skip fresh buckets —
            # self-heal by resetting the per-step state here
            if st["seq"] != -1 and (st["dispatched"] or st["in_backward"]):
                self._reset_overlap_step()
            st["seq"] = seq
        i = st["watch"].get(id(arr))
        if i is None:
            return
        rem = st["pending"].get(i, 0) - 1
        st["pending"][i] = rem
        if rem > 0:
            return
        self._ensure_overlap_plan()
        gi = st["group_of"].get(i)
        if gi is None or gi in st["dispatched"]:
            return
        keys = st["exchange"][0]
        if any(st["pending"].get(keys[pos], 1) > 0
               for pos in st["groups"][gi]):
            return
        self._dispatch_overlap_group(gi, during_backward=True)

    def _dispatch_overlap_group(self, gi, during_backward):
        st = self._overlap
        keys, grads, priorities = st["exchange"]
        grp = st["groups"][gi]
        self._kvstore.pushpull([keys[pos] for pos in grp],
                               [grads[pos] for pos in grp],
                               out=[grads[pos] for pos in grp],
                               priority=[priorities[pos] for pos in grp])
        st["dispatched"].add(gi)
        if during_backward:
            st["in_backward"] += 1
        telemetry.record_kv_overlap(
            "backward" if during_backward else "step")

    def _overlap_flush(self):
        """Dispatch every not-yet-exchanged group (params whose grads
        never finalized through the hook this step), record stats, and
        reset the per-step tracking."""
        st = self._overlap
        self._ensure_overlap_plan()
        for gi in range(len(st["groups"])):
            if gi not in st["dispatched"]:
                self._dispatch_overlap_group(gi, during_backward=False)
        self.last_overlap_stats = {
            "groups": len(st["groups"]),
            "dispatched_in_backward": st["in_backward"],
        }
        self._reset_overlap_step()

    def _reset_overlap_step(self):
        st = self._overlap
        st["dispatched"].clear()
        st["in_backward"] = 0
        for i in st["idxs"]:
            st["pending"][i] = len(self._params[i].list_ctx())

    # ------------------------------------------------------------------
    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    # ------------------------------------------------------------------
    def step(self, batch_size, ignore_stale_grad=False):
        """Apply one optimizer step scaled by 1/batch_size
        (reference: Trainer.step). With ``check_nonfinite``, a step with
        NaN/Inf gradients is skipped and counted instead (see class
        docstring)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        if self._check_nonfinite and \
                getattr(self, "_amp_loss_scaler", None) is None and \
                self._grads_nonfinite():
            # skip BEFORE the allreduce: a NaN local gradient would
            # poison every replica through the psum. (Single-process
            # semantics; a multi-process job must skip symmetrically or
            # replicas diverge — the AMP scaler path has the same
            # contract in the reference.)
            self.steps_skipped += 1
            telemetry.record_step_skipped("nonfinite_grad")
            logging.warning(
                "Trainer.step: non-finite gradient detected, skipping "
                "update (%d skipped so far)", self.steps_skipped)
            return
        self._allreduce_grads()
        self._update(ignore_stale_grad)

    def _grads_nonfinite(self) -> bool:
        """True if any live gradient contains NaN/Inf (the anomaly-guard
        scan; same contract as amp.DynamicLossScaler.has_overflow).
        One device->host sync for the whole parameter set: per-gradient
        isfinite reductions are AND-folded per device and fetched with a
        single batched ``device_get`` — N separate ``bool(...)`` pulls
        would serialize N round-trips into every guarded step."""
        import jax
        import jax.numpy as jnp

        by_dev = {}
        for p in self._params:
            if p.grad_req == "null":
                continue
            for g in p.list_grad():
                data = g.data
                dev = next(iter(data.devices())) \
                    if hasattr(data, "devices") else None
                flag = jnp.isfinite(data).all()
                prev = by_dev.get(dev)
                by_dev[dev] = flag if prev is None \
                    else jnp.logical_and(prev, flag)
        if not by_dev:
            return False
        return not all(bool(v) for v in
                       jax.device_get(list(by_dev.values())))

    def allreduce_grads(self):
        """Reduce gradients only — for gradient clipping between reduce and
        update (reference: Trainer.allreduce_grads)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        """Exchange gradients through the kvstore.

        Worker-side-optimizer mode (the tpu_sync/local default) goes
        through ONE batched ``pushpull``: the store coalesces the keys
        into flat ~``MXNET_KV_BUCKET_MB`` buckets and runs one
        collective per bucket instead of one per parameter, dispatching
        buckets in the ``priority=-i`` order (the hint the reference
        engine used for comms/compute overlap — honored here: bucket
        *i+1*'s allreduce is issued before bucket *i*'s scatter, so via
        JAX async dispatch it overlaps the scatter + optimizer update).
        Server-side-optimizer mode keeps per-key pushes — the updater
        applies per key on the store."""
        if self._kvstore is None:
            return
        if self._update_on_kvstore:
            for i, p in enumerate(self._params):
                if p.grad_req == "null":
                    continue
                self._kvstore.push(i, p.list_grad(), priority=-i)
            return
        if self._overlap is not None:
            # overlapped mode: buckets whose members finalized during
            # backward() were already exchanged from the grad-ready hook;
            # flush the stragglers and reset for the next step
            self._overlap_flush()
            return
        keys, grads, priorities = self._grad_exchange_args()
        if keys:
            self._kvstore.pushpull(keys, grads, out=grads,
                                   priority=priorities)

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        if self._update_on_kvstore:
            for i, p in enumerate(self._params):
                if p.grad_req == "null":
                    continue
                self._kvstore.pull(i, p.list_data(), priority=-i)
            return
        if self._zero is not None:
            # sharded sweep for the partitioned members; leftovers
            # (sparse / multi-precision) keep the per-param path — their
            # gradients DID go through the kvstore exchange above
            self._zero.step()
            zero_keys = set(self._zero.eligible_indices())
            for i, p in enumerate(self._params):
                if p.grad_req == "null" or i in zero_keys:
                    continue
                for ci, (upd, arr, grad) in enumerate(
                        zip(self._updaters, p.list_data(), p.list_grad())):
                    self._optimizer._set_current_context(ci)
                    telemetry.record_optimizer_dispatch("per_param")
                    upd(i, grad, arr)
            self._optimizer._set_current_context(0)
            return
        if self._fused_update():
            return
        # each context updates on its OWN count stream: a param updated
        # on N devices advances t once per step per device, so the
        # replicas (post-allreduce grads are identical) stay identical
        # under t-dependent updates (Adam bias correction)
        for i, p in enumerate(self._params):
            if p.grad_req == "null":
                continue
            for ci, (upd, arr, grad) in enumerate(
                    zip(self._updaters, p.list_data(), p.list_grad())):
                self._optimizer._set_current_context(ci)
                telemetry.record_optimizer_dispatch("per_param")
                upd(i, grad, arr)
        self._optimizer._set_current_context(0)

    def _fused_update(self) -> bool:
        """The horizontally-fused optimizer phase: pack every dense
        trainable param of like dtype into one bucket and apply the
        whole update as ONE jitted multi-tensor sweep per bucket
        (optimizer/multi_tensor.py) — O(params) eager dispatches
        collapse to O(dtype buckets). Engages for the fused families
        (SGD/Adam/AdamW/LAMB, exact class) unless
        ``MXNET_FUSED_OPTIMIZER=0``; row-sparse-grad params keep the
        per-param path (their updater owns the lazy-row contract).
        Bit-identical to the per-param loop — the test gate."""
        from ..optimizer import multi_tensor as mt

        if not mt.fused_sweep_enabled() \
                or mt.family_of(self._optimizer) is None:
            return False
        if len(self._updaters) > 1 \
                and self._optimizer.lr_scheduler is not None:
            # per-param interleaves contexts per index, so mid-step
            # num_update (the scheduler clock) evolves differently than
            # a per-context sweep would see — keep the reference order
            return False
        dense, sparse = [], []
        for i, p in enumerate(self._params):
            if p.grad_req == "null":
                continue
            (sparse if getattr(p, "grad_stype", "default") == "row_sparse"
             else dense).append(i)
        if not dense:
            return False
        per_ctx_items = [
            [(i, self._params[i].list_data()[ci],
              self._params[i].list_grad()[ci]) for i in dense]
            for ci in range(len(self._updaters))]
        # plan EVERY context before applying ANY sweep: a fallback
        # after context 0 already swept would re-run the per-param loop
        # over it too (double update). The plans carry the validated
        # bucket/state layout, so nothing is recomputed at apply time
        plans = [mt.plan_eager(self._optimizer, upd, items)
                 for upd, items in zip(self._updaters, per_ctx_items)]
        if any(p is None for p in plans):
            return False    # unfusable state layout: per-param loop
        # per-context count streams (see _update): each context's sweep
        # advances its own clock so every replica sees the same t
        for ci, (plan, items) in enumerate(zip(plans, per_ctx_items)):
            self._optimizer._set_current_context(ci)
            mt.apply_eager_plan(self._optimizer, plan, items)
        for i in sparse:
            p = self._params[i]
            for ci, (upd, arr, grad) in enumerate(
                    zip(self._updaters, p.list_data(), p.list_grad())):
                self._optimizer._set_current_context(ci)
                telemetry.record_optimizer_dispatch("per_param")
                upd(i, grad, arr)
        self._optimizer._set_current_context(0)
        return True

    # ------------------------------------------------------------------
    # envelope marker for trainer-state payloads that carry gradient-
    # compression error-feedback residuals next to the updater pickle;
    # plain payloads (no compression) keep the legacy bare-updater bytes
    _STATES_ENVELOPE = "__mxnet_tpu_trainer_states__"

    def save_states(self, fname):
        """reference: Trainer.save_states (Updater.get_states pickle).
        Committed atomically (temp + fsync + rename) — a crash mid-save
        leaves the previous state file intact. With gradient compression
        active, the error-feedback residuals ride along so a resumed
        run's transmitted-gradient stream continues bit-exactly."""
        if not self._kv_initialized:
            self._init_kvstore()
        from ..checkpoint import atomic_write

        blob = self._updaters[0].get_states(dump_optimizer=False)
        comp = getattr(self._kvstore, "_compression", None) \
            if self._kvstore is not None else None
        if comp is not None or self._zero is not None:
            import pickle

            env = {self._STATES_ENVELOPE: 1, "updater": blob}
            if comp is not None:
                env["compression"] = comp.get_state()
            if self._zero is not None:
                # the sharded payload names its partition plan + world
                # size; load_states refuses a mismatched plan with a
                # typed PartitionMismatchError instead of restoring
                # garbage
                env["zero"] = self._zero.export_state()
            blob = pickle.dumps(env)
        atomic_write(fname, blob)

    def load_states(self, fname):
        """Inverse of save_states. Missing or corrupt state files raise
        :class:`MXNetError` naming the file — never a raw OSError or
        pickle traceback from deep inside the updater."""
        if not self._kv_initialized:
            self._init_kvstore()
        from ..checkpoint import apply_state_bytes, read_state_bytes

        states = read_state_bytes(fname, "Trainer.load_states")

        def _apply(blob):
            comp_state = None
            zero_blob = None
            try:
                import pickle

                obj = pickle.loads(blob)
            except Exception:
                obj = None
            if isinstance(obj, dict) and obj.get(self._STATES_ENVELOPE):
                comp_state = obj.get("compression")
                zero_blob = obj.get("zero")
                blob = obj["updater"]
            from ..optimizer.zero import PartitionMismatchError

            if self._zero is not None:
                if zero_blob is None:
                    raise PartitionMismatchError(
                        f"{fname!r} holds replicated (unpartitioned) "
                        f"trainer state but this trainer runs partition "
                        f"plan [{self._zero.describe()}] — save under "
                        "the same partition mode or load into an "
                        "unpartitioned trainer")
                self._zero.check_compatible(zero_blob)
            elif zero_blob is not None:
                from ..optimizer.zero import _plan_digest

                src = _plan_digest(zero_blob.get("plan", []),
                                   zero_blob.get("mode"),
                                   zero_blob.get("world"))
                raise PartitionMismatchError(
                    f"{fname!r} holds sharded optimizer state (plan "
                    f"[{src}]) but this trainer is unpartitioned — "
                    "construct the Trainer with the matching "
                    "partition= mode to restore it")
            comp = getattr(self._kvstore, "_compression", None) \
                if self._kvstore is not None else None
            if comp_state is not None:
                if comp is None:
                    raise MXNetError(
                        f"{fname!r} carries gradient-compression "
                        "residual state but this Trainer has no "
                        "compression_params configured")
                comp.set_state(comp_state)
            elif comp is not None:
                # legacy/residual-less payload into a compressing
                # trainer: clear any live residuals so the restored
                # stream matches a fresh process loading the same file
                comp.set_state({})
            for upd in self._updaters:
                upd.set_states(blob)
                if upd.optimizer is not self._optimizer:
                    # a dump_optimizer=True payload installed its own
                    # Optimizer on the updater; carry its restored update
                    # counters onto the Trainer's live optimizer before
                    # re-pointing, or the Adam bias-correction clock the
                    # v2 state format preserves would be silently lost
                    self._optimizer.num_update = upd.optimizer.num_update
                    self._optimizer._restore_update_counts(
                        upd.optimizer._index_update_count)
                upd.optimizer = self._optimizer
            if self._zero is not None:
                self._zero.import_state([zero_blob])

        apply_state_bytes(states, _apply, fname, "Trainer.load_states")

    def load_states_resharded(self, fnames):
        """Gather per-rank sharded state files — possibly saved at a
        DIFFERENT world size or bucket layout — and re-shard them into
        this trainer's partition plan (the elastic N→M rejoin path).

        ``fnames`` must cover every rank of the source world (each file
        an envelope from a partitioned :meth:`save_states`); a missing
        rank raises a typed
        :class:`~mxnet_tpu.optimizer.zero.PartitionMismatchError`.
        Updater (leftover-param) and compression state are taken from
        the first file — under the synchronous contract every rank
        holds the same replicated copy of those.
        """
        if not self._kv_initialized:
            self._init_kvstore()
        if self._zero is None:
            raise MXNetError(
                "load_states_resharded requires an active partition= "
                "mode; use load_states for replicated trainer state")
        from ..checkpoint import apply_state_bytes, read_state_bytes
        from ..optimizer.zero import PartitionMismatchError

        fnames = list(fnames)
        if not fnames:
            raise MXNetError("load_states_resharded: no state files")
        payloads = []
        head_updater = None
        head_comp = None
        for fname in fnames:
            states = read_state_bytes(fname,
                                      "Trainer.load_states_resharded")

            def _parse(blob, _fname=fname):
                import pickle

                obj = pickle.loads(blob)
                if not (isinstance(obj, dict)
                        and obj.get(self._STATES_ENVELOPE)
                        and obj.get("zero") is not None):
                    raise PartitionMismatchError(
                        f"{_fname!r} does not hold sharded trainer "
                        "state (no partition envelope) — it cannot "
                        "join a re-shard")
                return obj

            box = []
            apply_state_bytes(states, lambda b: box.append(_parse(b)),
                              fname, "Trainer.load_states_resharded")
            obj = box[0]
            payloads.append(obj["zero"])
            if head_updater is None:
                head_updater = obj["updater"]
                head_comp = obj.get("compression")
        comp = getattr(self._kvstore, "_compression", None) \
            if self._kvstore is not None else None
        if comp is not None:
            comp.set_state(head_comp or {})
        for upd in self._updaters:
            upd.set_states(head_updater)
            upd.optimizer = self._optimizer
        self._zero.import_state(payloads)
