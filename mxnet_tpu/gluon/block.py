"""Gluon Block / HybridBlock.

Reference: ``python/mxnet/gluon/block.py :: Block`` (children tree, param
collection, hooks, initialize, save/load_parameters) and ``:: HybridBlock``
(`hybridize()` → CachedOp, `export()`, deferred shape inference).

TPU-native CachedOp (SURVEY.md §3.3 — "THE lowering seam"): MXNet's
``HybridBlock._build_cache`` traces ``hybrid_forward`` into an nnvm graph
and runs it via ``src/imperative/cached_op.cc`` with static memory planning
and op bulking. Here ``hybridize()`` wraps the block's forward in ONE
``jax.jit`` executable per (input shapes, dtypes, train-flag) key:

* static_alloc ≙ XLA buffer allocation, bulking ≙ XLA fusion — both free;
* parameters enter as executable inputs so autograd can differentiate the
  whole fused step via one ``jax.vjp``;
* in-place aux-state writes during the trace (BatchNorm moving stats) are
  captured by ``mxnet_tpu.mutation`` and returned as extra outputs, then
  written back — the functional re-design of MXNet's mutable aux states;
* random ops draw from a per-call PRNG key input, so one compiled
  executable yields fresh dropout masks per step with zero recompiles.
"""
from __future__ import annotations

import functools
import re
import threading
from collections import OrderedDict
from typing import List, Optional

from .. import autograd, engine, mutation, random_state
from ..base import MXNetError, name_manager
from ..context import Context, cpu, current_context
from ..ndarray import NDArray
from ..ndarray.ndarray import _wrap_jax, imperative_invoke, _LambdaOp
from .parameter import DeferredInitializationError, Parameter, ParameterDict

__all__ = ["Block", "HybridBlock", "SymbolBlock", "nested_flatten_nd",
           "remat_call", "resolve_remat_policy"]


def resolve_remat_policy(policy):
    """Normalize a remat policy name to a ``jax.checkpoint`` policy.

    The single validator behind every remat surface (``remat_call``, the
    model zoo's ``remat=`` kwargs, ``TrainStep(remat=...)``), so a typo
    raises the same ValueError everywhere — eagerly, never from inside a
    trace. Returns the jax policy callable (or None for save-nothing):

      None / "full"  save nothing — recompute the whole span;
      "dots"         ``dots_with_no_batch_dims_saveable`` — matmul
                     outputs SAVED, elementwise/norm/rotary recompute;
      callable       passed through (a raw jax checkpoint policy).
    """
    import jax

    if policy in (None, "full"):
        return None
    if policy == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    if callable(policy):
        return policy
    raise ValueError(f"unknown remat policy {policy!r}")


def remat_call(block, *args, policy=None):
    """Call ``block`` under ``jax.checkpoint`` when inside a live trace.

    Gradient rematerialization for big models (SURVEY.md §7.2 "remat
    policy"): inside a compiled train step the block's activations are
    recomputed in the backward pass instead of saved — HBM for FLOPs, the
    standard trade for transformer trunks. Parameters reach the block as
    closed-over trace inputs and stay saved; only intra-block activations
    are recomputed. Outside a trace (eager) this is a plain call: eager
    autograd replays the graph anyway, so there is nothing to save.

    ``policy``:
      None / "full"  save nothing — recompute the whole block (max memory
                     savings, ~+1 forward of FLOPs per backward);
      "dots"         ``dots_with_no_batch_dims_saveable`` — matmul outputs
                     are SAVED, only elementwise/norm/rotary recompute.
                     The backward re-runs no MXU work, so the remat FLOPs
                     tax ~vanishes for ~the matmul-output bytes per block
                     (the middle ground when full activations don't fit
                     but matmul outputs do — see PERF.md round 4 for the
                     measured policy ladder on the 0.7B proxy).
    """
    import jax

    from ..ndarray import NDArray

    # validate the policy on EVERY call (eager included) so a typo can't
    # hide until the first traced step
    jpolicy = resolve_remat_policy(policy)

    if not args or not isinstance(args[0].data, jax.core.Tracer):
        return block(*args)
    ctx = args[0].context

    def _pure(*vals):
        out = block(*[NDArray(data=v, ctx=ctx) for v in vals])
        flat, tree = nested_flatten_nd(out)
        _pure.tree = tree
        return tuple(o.data for o in flat)

    out_vals = jax.checkpoint(_pure, policy=jpolicy)(*[a.data for a in args])
    out_nd = [NDArray(data=v, ctx=ctx) for v in out_vals]
    return nested_unflatten_nd(_pure.tree, out_nd)


class _BlockScope(threading.local):
    """Name scope for automatic prefixing (reference: block.py::_BlockScope)."""

    def __init__(self):
        super().__init__()
        self.current = None

    @staticmethod
    def create(prefix, params, hint):
        scope = _scope
        if scope.current is None:
            if prefix is None:
                prefix = name_manager.get(None, hint) + "_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, shared=params)
            return prefix, params
        block = scope.current
        if prefix is None:
            prefix = name_manager.get(None, hint) + "_"
        if params is None:
            parent = block._block._params
            params = ParameterDict(parent.prefix + prefix, shared=None)
        else:
            params = ParameterDict(params.prefix, shared=params)
        return block._block.prefix + prefix, params


_scope = _BlockScope()


class _NameScopeCtx:
    """One ctx per Block, REUSED across ``with`` statements — so saved
    outer scopes live on a stack: re-entering the same block's scope
    (e.g. a helper taking ``parent.name_scope()`` while the parent's
    __init__ is already inside it) must not clobber the saved outer
    scope with ``self`` and leak the scope process-wide."""

    def __init__(self, block):
        self._block = block
        self._olds = []

    def __enter__(self):
        self._olds.append(_scope.current)
        _scope.current = self
        return self

    def __exit__(self, *exc):
        _scope.current = self._olds.pop()


class Block:
    """Base building block (reference: gluon/block.py::Block)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") else self._prefix
        self._scope = _NameScopeCtx(self)
        self._children = OrderedDict()
        self._reg_params = {}
        self._forward_hooks = OrderedDict()
        self._forward_pre_hooks = OrderedDict()
        self._hook_id = 0

    def _alias(self):
        return self.__class__.__name__.lower()

    # ------------------------------------------------------------------
    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    @property
    def params(self) -> ParameterDict:
        return self._params

    def name_scope(self):
        return self._scope

    def collect_params(self, select: Optional[str] = None) -> ParameterDict:
        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update(self._params)
        else:
            pat = re.compile(select)
            ret.update({k: v for k, v in self._params.items() if pat.match(k)})
        for child in self._children.values():
            ret.update(child.collect_params(select))
        return ret

    # ------------------------------------------------------------------
    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = self.__dict__.get("_children")
            if existing is not None:
                existing[name] = value
        elif isinstance(value, Parameter):
            reg = self.__dict__.get("_reg_params")
            if reg is not None:
                reg[name] = value
        super().__setattr__(name, value)

    def register_child(self, block, name=None):
        self._children[name or str(len(self._children))] = block
        return block

    def register_forward_hook(self, hook):
        self._hook_id += 1
        self._forward_hooks[self._hook_id] = hook
        return _HookHandle(self._forward_hooks, self._hook_id)

    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return _HookHandle(self._forward_pre_hooks, self._hook_id)

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for p in self._params.values():
            p.cast(dtype)

    # ------------------------------------------------------------------
    def __call__(self, *args):
        for hook in self._forward_pre_hooks.values():
            hook(self, args)
        out = self.forward(*args)
        for hook in self._forward_hooks.values():
            hook(self, args, out)
        return out

    def forward(self, *args):
        raise NotImplementedError

    def summary(self, *inputs):
        rows = []

        def add_hooks(blk, path):
            hs = []
            for name, child in blk._children.items():
                hs += add_hooks(child, f"{path}.{name}")
            h = blk.register_forward_hook(
                lambda b, i, o, path=path: rows.append(
                    (path, type(b).__name__,
                     getattr(o[0] if isinstance(o, (list, tuple)) else o, "shape", None))))
            hs.append(h)
            return hs

        handles = add_hooks(self, self._name)
        try:
            self(*inputs)
        finally:
            for h in handles:
                h.detach()
        lines = [f"{'Layer':<40}{'Type':<25}{'Output shape'}"]
        lines += [f"{p:<40}{t:<25}{s}" for p, t, s in rows]
        return "\n".join(lines)

    # ------------------------------------------------------------------
    def save_parameters(self, filename, deduplicate=False):
        """reference: Block.save_parameters — params only, keyed by the
        block-relative name so models are prefix-independent."""
        params = self._collect_params_with_prefix()
        from ..ndarray import serialization

        serialization.save(filename, {k: v.data().as_in_context(cpu(0))
                                      for k, v in params.items()})

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False,
                        dtype_source="current"):
        from ..ndarray import serialization

        loaded = serialization.load(filename)
        if isinstance(loaded, list):
            raise MXNetError(f"{filename} holds a list, not a parameter dict")
        # an optimize_for graph holds folded COPIES of the old params; it
        # must not keep serving after a checkpoint restore
        if getattr(self, "_optimized_block", None) is not None:
            self._set_optimized_block(None)
        loaded = {k[4:] if k.startswith(("arg:", "aux:")) else k: v
                  for k, v in loaded.items()}
        params = self._collect_params_with_prefix()
        if not allow_missing:
            for name in params:
                if name not in loaded:
                    # name the keys the file DOES hold: a prefix mismatch
                    # ('features.0.weight' vs '0.weight') is then obvious
                    # from the error alone instead of a debugger session
                    avail = sorted(loaded)
                    shown = ", ".join(avail[:12]) + \
                        (f", ... ({len(avail) - 12} more)"
                         if len(avail) > 12 else "")
                    raise MXNetError(
                        f"Parameter {name} missing in {filename} "
                        f"(allow_missing=False). The file contains "
                        f"{len(avail)} parameter(s): [{shown}]")
        for name, v in loaded.items():
            if name not in params:
                if not ignore_extra:
                    raise MXNetError(
                        f"{filename} contains extra parameter {name} "
                        "(ignore_extra=False)")
                continue
            p = params[name]
            if cast_dtype:
                if dtype_source == "current" and p._data is not None:
                    v = v.astype(str(p.dtype))
                elif dtype_source == "saved":
                    p.dtype = str(v.dtype)
            if p._data is None and p._deferred_init is None:
                p.initialize(ctx=ctx or cpu(0))
            p.set_data(v)

    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + name: p for name, p in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    def hybridize(self, active=True, **kwargs):
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def __repr__(self):
        s = f"{self.__class__.__name__}(\n"
        for name, child in self._children.items():
            s += f"  ({name}): {repr(child)}\n"
        return s + ")"


class _HookHandle:
    def __init__(self, hooks, hid):
        self._hooks = hooks
        self._id = hid

    def detach(self):
        self._hooks.pop(self._id, None)


def nested_flatten_nd(out):
    """Flatten nested (tuple/list of) NDArray into a flat list + treedef."""
    flat = []

    def walk(o):
        if isinstance(o, NDArray):
            flat.append(o)
            return ("leaf", len(flat) - 1)
        if isinstance(o, (list, tuple)):
            return ("seq", type(o).__name__, [walk(x) for x in o])
        raise MXNetError(f"hybrid forward returned unsupported type {type(o)}")

    tree = walk(out)
    return flat, tree


def nested_unflatten_nd(tree, flat):
    kind = tree[0]
    if kind == "leaf":
        return flat[tree[1]]
    _, tname, children = tree
    seq = [nested_unflatten_nd(c, flat) for c in children]
    return tuple(seq) if tname == "tuple" else seq


def make_pure_fn(block, param_arrays, ctx, training):
    """Build a pure function over a Block's forward.

    Returns ``(pure, cell)`` where ``pure(param_vals, rng, *input_vals) ->
    (out_vals, aux_vals)`` is jax-traceable and ``cell`` carries the output
    treedef plus the aux-state NDArrays mutated during the trace (BatchNorm
    moving stats etc. — see mxnet_tpu.mutation). This is the single lowering
    seam shared by CachedOp (hybridize) and the sharded train step
    (mxnet_tpu.parallel.step); reference: src/imperative/cached_op.cc.
    """

    def pure(param_vals, rng, *input_vals):
        prev_rec = autograd.set_recording(False)
        prev_train = autograd.set_training(training)
        olds = [arr._data for arr in param_arrays]
        with mutation.mutation_scope() as log:
            with random_state.scoped_key(rng):
                try:
                    for arr, v in zip(param_arrays, param_vals):
                        arr._data = v
                        arr._version += 1
                    nd_in = [NDArray(data=v, ctx=ctx) for v in input_vals]
                    out = block._eager_forward(*nd_in)
                    flat, tree = nested_flatten_nd(out)
                    aux_arrays = [a for a in log.arrays]
                    cell["aux_arrays"] = aux_arrays
                    cell["treedef"] = tree
                    cell["n_out"] = len(flat)
                    out_vals = tuple(o.data for o in flat)
                    aux_vals = tuple(a.data for a in aux_arrays)
                    return out_vals, aux_vals
                finally:
                    # restore any concrete payloads clobbered by tracers:
                    # first logged mutations, then the param swaps
                    for a, orig in log.originals:
                        a._data = orig
                        a._version += 1
                    for arr, old in zip(param_arrays, olds):
                        arr._data = old
                        arr._version += 1
                    autograd.set_recording(prev_rec)
                    autograd.set_training(prev_train)

    cell = {"aux_arrays": None, "treedef": None, "n_out": None}
    return pure, cell


class _CachedGraph:
    """One compiled executable per (shapes, dtypes, train-flag) key — the
    jax.jit equivalent of ``src/imperative/cached_op.cc :: CachedOp``.

    Routed through the compilation service: canonical signature keying
    (``compiler.signature``), executables AOT-compiled via
    ``jit(...).lower().compile()`` and deduped across architecturally
    identical blocks through the in-process executable table (replica N
    of a Router reuses replica 0's XLA compile), every build journaled to
    the signature manifest for :func:`mxnet_tpu.compiler.warm_start`.
    """

    def __init__(self, block, flags):
        from ..compiler import service as _csvc

        self.block = block
        self.flags = dict(flags or {})
        self._cache = _csvc.SiteCache("cached_op")
        self._cells = {}     # training-flag -> cell memo (see _build)

    def clear(self):
        self._cache.clear()

    def _key_for(self, args, param_arrays, training):
        from ..compiler import signature

        # trace-time routing knobs (Pallas fused kernels, hash dropout)
        # select different op bodies — they key the cache like shapes do
        return signature(
            "cached_op", id(self.block),
            avals=tuple((tuple(a.shape), str(a.dtype)) for a in args),
            extra=(tuple((tuple(a.shape), str(a.dtype))
                         for a in param_arrays), training))

    def __call__(self, args: List[NDArray]):
        block = self.block
        ctx = args[0].context if args else current_context()
        params = [p for p in block.collect_params().values()]
        # deferred shapes must be settled before tracing
        if any(p._data is None for p in params):
            raise DeferredInitializationError  # caller runs one eager pass
        param_arrays = [p.data(ctx) for p in params]
        training = autograd.is_training()
        key = self._key_for(args, param_arrays, training)
        entry = self._cache.lookup(key)
        if entry is self._cache.MISS:
            entry = self._build(param_arrays, args, ctx, training)
            self._cache.insert(key, entry)
        jitted, cell = entry["jitted"], entry["cell"]
        rng = random_state.get_state_key()

        n_params = len(param_arrays)

        def call_fn(*tensors):
            pvals = tensors[:n_params]
            ivals = tensors[n_params:]
            outs, aux = jitted(tuple(pvals), rng, *ivals)
            return tuple(outs) + tuple(aux)

        results = imperative_invoke(
            _LambdaOp(call_fn, f"CachedOp_{block.name}"),
            list(param_arrays) + list(args), {}, ctx=ctx)
        if not isinstance(results, list):
            results = [results]
        n_out = cell["n_out"]
        out_nd = results[:n_out]
        aux_nd = results[n_out:]
        for arr, v in zip(cell["aux_arrays"], aux_nd):
            arr._set_data(v.data)
        return nested_unflatten_nd(cell["treedef"], out_nd)

    def _build(self, param_arrays, args, ctx, training):
        import jax

        pure, cell = make_pure_fn(self.block, param_arrays, ctx, training)
        # training-mode graphs run under autograd recording (jax.vjp over
        # call_fn) where a Compiled cannot serve — sealing would compile
        # an executable whose every use is the tracer fallback; plain jit
        # traces once and serves both. Inference graphs (the serving warm
        # path) seal through the service.
        if training:
            return {"jitted": jax.jit(pure), "cell": cell}
        jitted = None
        try:
            from .. import compiler
            from ..compiler import service as _csvc

            # AOT through the service's persistence stack: the canonical
            # signature (graph structure + forward bytecode + avals +
            # routing + platform) keys the in-process executable table —
            # replica N of one architecture reuses replica 0's XLA
            # compile — and the exported-StableHLO blob store, so a
            # fresh process skips the trace too. The trace (when one
            # runs) settles `cell`; a blob hit settles it via the
            # cell-shape probe below.
            psds = tuple(jax.ShapeDtypeStruct(tuple(a.shape), a.data.dtype)
                         for a in param_arrays)
            isds = tuple(jax.ShapeDtypeStruct(tuple(a.shape), a.data.dtype)
                         for a in args)
            with random_state.preserved_stream():
                rng = random_state.get_state_key()
            rsds = jax.ShapeDtypeStruct(tuple(rng.shape), rng.dtype)
            graph = compiler.graph_ident(self.block)
            arg_avals = tuple((tuple(a.shape), str(a.data.dtype))
                              for a in args)
            sig_fp = compiler.keys.fingerprint(compiler.keys.encode((
                "cached_op", graph,
                tuple((tuple(a.shape), str(a.data.dtype))
                      for a in param_arrays),
                arg_avals, (tuple(rng.shape), str(rng.dtype)), training,
                compiler.routing_knobs(),
                jax.default_backend(), jax.__version__)))
            jitted = _csvc.seal_executable(
                sig_fp, jax.jit(pure), (psds, rsds) + isds,
                fallback=functools.partial(jax.jit, pure))
            if cell["treedef"] is None:
                # exported-blob hit: nothing traced `pure`, so the cell
                # (output treedef + aux arrays) is still unset — reuse
                # the memo from a sibling signature (structure is a
                # property of the block, not the batch shape), else
                # settle it with one host-side shape probe (no compile)
                memo = self._cells.get(training)
                if memo is not None:
                    cell.update(memo)
                else:
                    jax.eval_shape(pure, psds, rsds, *isds)
            if cell["treedef"] is not None:
                self._cells[training] = {
                    k: cell[k]
                    for k in ("aux_arrays", "treedef", "n_out")}
            compiler.record_signature("cached_op", {
                "graph": graph, "args": arg_avals, "training": training,
                "routing": compiler.routing_knobs()})
        except Exception:
            # AOT lowering is an optimization; blocks whose forward needs
            # concrete values (or exotic placements) keep the trace-at-
            # first-call jit path
            jitted = None
        if jitted is None:
            jitted = jax.jit(pure)
        return {"jitted": jitted, "cell": cell}

    def warm_spec(self, spec) -> str:
        """AOT-compile one recorded ``cached_op`` manifest entry against
        this graph's live block — no real dispatch, just
        ``jit(...).lower().compile()`` through the executable table.
        Returns the warm outcome ("replayed"/"deduped"/"skipped")."""
        from .. import autograd as _ag
        from ..ndarray import zeros as _nd_zeros

        arg_avals = spec.get("args") or ()
        args = [_nd_zeros(tuple(shape), dtype=dtype)
                for shape, dtype in arg_avals]
        if not args:
            return "skipped"
        training = bool(spec.get("training", False))
        block = self.block
        params = [p for p in block.collect_params().values()]
        if any(p._data is None for p in params):
            try:
                with _ag.pause():
                    block._deferred_infer_shape(*args)
            except Exception:
                return "skipped"    # warm cannot settle this graph
            params = [p for p in block.collect_params().values()]
        ctx = args[0].context
        param_arrays = [p.data(ctx) for p in params]
        key = self._key_for(args, param_arrays, training)
        if key in self._cache:
            return "deduped"
        prev = _ag.set_training(training)
        try:
            entry = self._build(param_arrays, args, ctx, training)
        finally:
            _ag.set_training(prev)
        self._cache.insert(key, entry)
        return "replayed"

    def warmup(self, arg_specs, dtype="float32", ctx=None):
        """AOT-compile one cache entry per input signature, ahead of any
        real request (first bite of ROADMAP item 5 — a serving replica
        must start hot, not pay first-request trace+compile latencies).

        ``arg_specs``: iterable of input signatures. Each spec is either
        one shape tuple (single-input block) or a sequence of shape
        tuples (multi-input); ``dtype`` applies to every input, or pass
        ``(shape, dtype)`` pairs inside a multi-input spec-style list to
        mix — shapes whose first element is an ``int`` are treated as a
        single input.

        Drives a real zero-filled call through ``__call__`` per spec
        (inference mode, gradient tape paused), so both the trace cache
        here AND jax's executable cache are warm — a later request with
        that signature is a pure cache hit. A signature already seated
        by an AOT warm (manifest replay, a previous warmup) is skipped
        without dispatching — its executable exists, re-executing it
        would only burn device time per bucket per reload. Returns the
        number of entries newly compiled (0 = everything was already
        warm).
        """
        from .. import autograd as _ag
        from ..ndarray import zeros as _nd_zeros

        before = len(self._cache)
        for spec in arg_specs:
            spec = list(spec) if not (spec and isinstance(spec[0], int)) \
                else [tuple(spec)]
            args = []
            for item in spec:
                if (len(item) == 2 and isinstance(item[0], (tuple, list))
                        and isinstance(item[1], str)):
                    shape, dt = tuple(item[0]), item[1]
                else:
                    shape, dt = tuple(item), dtype
                args.append(_nd_zeros(shape, ctx=ctx, dtype=dt))
            with _ag.pause():
                if self._is_warm(args):
                    continue
                try:
                    self(args)
                except DeferredInitializationError:
                    self.block._deferred_infer_shape(*args)
                    self(args)
        return len(self._cache) - before

    def _is_warm(self, args) -> bool:
        """Whether this exact call signature already has a compiled
        entry (telemetry-silent — a warmup probe is not a serving
        lookup)."""
        params = [p for p in self.block.collect_params().values()]
        if any(p._data is None for p in params):
            return False
        ctx = args[0].context if args else current_context()
        param_arrays = [p.data(ctx) for p in params]
        key = self._key_for(args, param_arrays, autograd.is_training())
        return key in self._cache


def warm_cached_op_spec(block, spec) -> str:
    """``compiler.warm_start``'s cached_op replay hook: seat one recorded
    input signature in ``block``'s graph cache, AOT-compiled. The block
    is hybridized if it is not already (a warm target must serve through
    the compiled path for the warm entry to be the one hit)."""
    if getattr(block, "_active", None) is False:
        block.hybridize()
    if block._cached_graph is None:
        block._cached_graph = _CachedGraph(block, block._flags)
    return block._cached_graph.warm_spec(spec)


class HybridBlock(Block):
    """Block that can be compiled to one XLA executable
    (reference: gluon/block.py::HybridBlock)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._flags = {}
        self._cached_graph = None

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  inline_limit=2, forward_bulk_size=None,
                  backward_bulk_size=None, **kwargs):
        """Compile this block (reference: HybridBlock.hybridize; the
        CachedOpConfig flags map to XLA behaviors — static_alloc/bulking are
        native to XLA, kept for API compat)."""
        self._active = active
        self._flags = {"static_alloc": static_alloc, "static_shape": static_shape}
        self._cached_graph = None
        # drop any optimize_for graph: its params are a folded COPY, so
        # it must not shadow the live params after a re-hybridize
        self._set_optimized_block(None)
        super().hybridize(active, static_alloc=static_alloc,
                          static_shape=static_shape, **kwargs)

    def _clear_cached_op(self):
        self._cached_graph = None

    def warmup(self, input_shapes, dtype="float32", ctx=None):
        """Pre-trace + compile the hybridized graph for every signature
        in ``input_shapes`` (see :meth:`_CachedGraph.warmup`) so no
        real request pays a first-call compile — the serving bucket
        grid's load-time hook. Requires :meth:`hybridize` first; returns
        the number of entries newly compiled."""
        if not self._active:
            raise MXNetError(
                f"{self.name}: warmup() requires hybridize() — only a "
                "compiled block has a graph cache to warm")
        if self._cached_graph is None:
            self._cached_graph = _CachedGraph(self, self._flags)
        return self._cached_graph.warmup(input_shapes, dtype=dtype, ctx=ctx)

    def cast(self, dtype):
        self._clear_cached_op()
        super().cast(dtype)

    def infer_shape(self, *args):
        """Resolve deferred parameter shapes from sample inputs."""
        self._deferred_infer_shape(*args)

    def _deferred_infer_shape(self, *args):
        with autograd.pause():
            self._eager_forward(*args)

    # ------------------------------------------------------------------
    def forward(self, *args):
        from ..symbol import Symbol as _Sym

        if args and isinstance(args[0], _Sym):
            return self._symbolic_forward(*args)
        opt = getattr(self, "_optimized_block", None)
        if opt is not None and args and isinstance(args[0], NDArray):
            # optimize_for swapped in a backend-transformed graph
            return opt(*args)
        if self._active and args and isinstance(args[0], NDArray) \
                and not mutation.is_tracing():
            if self._cached_graph is None:
                self._cached_graph = _CachedGraph(self, self._flags)
            try:
                return self._cached_graph(list(args))
            except DeferredInitializationError:
                self._deferred_infer_shape(*args)
                return self._cached_graph(list(args))
        return self._eager_forward(*args)

    def _symbolic_forward(self, *args):
        """Trace hybrid_forward with Symbol proxies (reference:
        HybridBlock._build_cache's CachedOp graph construction; here it
        serves `export()` → symbol.json). Parameters become variables named
        by their full parameter name, so the exported graph binds against
        the saved .params file."""
        from .. import symbol as sym_mod

        pdata = {}
        for name, p in self._reg_params.items():
            pdata[name] = sym_mod.var(p.name)
        return self.hybrid_forward(sym_mod, *args, **pdata)

    def _eager_forward(self, *args):
        """Un-compiled forward: resolve params and call hybrid_forward."""
        from .. import ndarray as nd_mod

        ctx = None
        for a in args:
            if isinstance(a, NDArray):
                ctx = a.context
                break
        if ctx is None:
            ctx = current_context()
        try:
            pdata = {name: p.data(ctx) for name, p in self._reg_params.items()}
        except DeferredInitializationError:
            self._infer_param_shapes(*args)
            pdata = {name: p.data(ctx) for name, p in self._reg_params.items()}
        return self.hybrid_forward(nd_mod, *args, **pdata)

    def _infer_param_shapes(self, *args):
        """Layer-specific deferred-shape resolution; layers with deferred
        params override (reference: the nnvm infer_shape pass feeding
        _finish_deferred_init)."""
        raise DeferredInitializationError(
            f"{self.name}: parameter shapes are unknown and "
            f"{type(self).__name__} does not implement shape inference; "
            "initialize with explicit shapes")

    def hybrid_forward(self, F, *args, **params):
        raise NotImplementedError

    def export(self, path, epoch=0):
        """Export architecture + params (reference: HybridBlock.export →
        prefix-symbol.json + prefix-%04d.params)."""
        from ..symbol.export import export_hybrid_block

        return export_hybrid_block(self, path, epoch)

    def optimize_for(self, x, backend=None, **kwargs):
        """Apply a subgraph backend to this block (reference:
        HybridBlock.optimize_for). With a backend: symbolically trace,
        run the backend's registered passes (mxnet_tpu.subgraph), and
        swap the block's forward to the transformed graph — the same
        replace-in-place contract as upstream. Without: just hybridize
        (XLA fuses natively).

        The swapped-in graph holds its own (possibly weight-FOLDED)
        parameter copies — an inference artifact. ``hybridize()`` or
        ``load_parameters()`` clears it and reconnects the live params;
        re-run optimize_for afterwards if wanted."""
        self.hybridize()
        if backend is None:
            return self(x)
        from .. import subgraph
        from ..symbol.export import trace_symbol

        sym, arg_params, aux_params = trace_symbol(self)
        sym = subgraph.apply_backend(backend, sym, arg_params, aux_params,
                                     **kwargs)
        opt = SymbolBlock(sym, self._sym_trace_inputs(sym, arg_params,
                                                      aux_params))
        for name, arr in list(arg_params.items()) + list(aux_params.items()):
            p = opt.collect_params()[name]
            p.shape = tuple(arr.shape)
            p.initialize(force_reinit=True)
            p.set_data(arr)
        self._set_optimized_block(opt)
        return self(x)

    def _set_optimized_block(self, blk):
        # bypass __setattr__: the swapped-in graph is an inference
        # artifact, NOT a child (its folded param copies must not appear
        # in collect_params / save_parameters)
        self.__dict__["_optimized_block"] = blk
        self._children.pop("_optimized_block", None)

    @staticmethod
    def _sym_trace_inputs(sym, arg_params, aux_params):
        from ..symbol import var

        return [var(n) for n in sym.list_arguments()
                if n not in arg_params and n not in aux_params]


class SymbolBlock(HybridBlock):
    """Import a symbolic graph as a Block (reference:
    gluon/block.py::SymbolBlock). Completed in mxnet_tpu/symbol."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="", params=params)
        self._sym_outputs = outputs
        self._sym_inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        from ..symbol import Symbol

        out = outputs if isinstance(outputs, Symbol) else outputs[0]
        self._out_sym = outputs
        # register params for every non-input argument of the graph
        input_names = {s.name for s in self._sym_inputs}
        for name in out.list_arguments():
            if name not in input_names:
                self._reg_params[name] = self.params.get(
                    name, allow_deferred_init=True)
        for name in out.list_auxiliary_states():
            self._reg_params[name] = self.params.get(
                name, grad_req="null", allow_deferred_init=True)

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        from ..symbol import load as sym_load, var

        sym = sym_load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        inputs = [var(n) for n in input_names]
        block = SymbolBlock(sym, inputs)
        if param_file is not None:
            block.load_parameters(param_file, ctx=ctx, cast_dtype=True,
                                  allow_missing=False, ignore_extra=False)
        return block

    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        return {prefix + name: p for name, p in self._reg_params.items()}

    def hybrid_forward(self, F, *args, **params):
        from ..symbol.executor import eval_symbol

        feed = {s.name: a for s, a in zip(self._sym_inputs, args)}
        feed.update(params)
        out = eval_symbol(self._out_sym, feed)
        return out
