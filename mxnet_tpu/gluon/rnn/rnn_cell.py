"""Recurrent cells.

Reference: ``python/mxnet/gluon/rnn/rnn_cell.py`` — RecurrentCell base
(begin_state, unroll, state_info), RNNCell, LSTMCell, GRUCell,
SequentialRNNCell, DropoutCell, ModifierCell (Zoneout/Residual),
BidirectionalCell.
"""
from __future__ import annotations

from ... import ndarray as nd
from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "DropoutCell", "ZoneoutCell",
           "ResidualCell", "BidirectionalCell", "ModifierCell", "HybridSequentialRNNCell"]


def _format_sequence(length, inputs, layout, merge):
    from ... import ndarray as F

    axis = layout.find("T")
    batch_axis = layout.find("N")
    if isinstance(inputs, (list, tuple)):
        in_list = list(inputs)
        batch = in_list[0].shape[0]
    else:
        if axis != 0:
            inputs = inputs.swapaxes(0, axis)
        batch = inputs.shape[1]
        in_list = [inputs[i] for i in range(inputs.shape[0])]
    return in_list, axis, batch


def _mask_sequence_variable_length(F, data, length, valid_length, time_axis,
                                   merge):
    raise NotImplementedError


class RecurrentCell(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, ctx=None, **kwargs):
        from ... import ndarray as F

        func = func or F.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            shape = info["shape"] if isinstance(info, dict) else info
            states.append(func(shape=shape, ctx=ctx, **kwargs))
        return states

    def __call__(self, inputs, states=None):
        self._counter += 1
        return super().__call__(inputs, states)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Unroll the cell over `length` steps (reference:
        RecurrentCell.unroll)."""
        from ... import ndarray as F

        self.reset()
        in_list, axis, batch = _format_sequence(length, inputs, layout, False)
        if begin_state is None:
            ctx = in_list[0].context
            begin_state = self.begin_state(batch, ctx=ctx,
                                           dtype=str(in_list[0].dtype))
        states = begin_state
        outputs = []
        all_states = [] if valid_length is not None else None
        for i in range(length):
            output, states = self(in_list[i], states)
            outputs.append(output)
            if all_states is not None:
                all_states.append(states)
        if valid_length is not None:
            stacked = F.stack(*outputs, axis=0)
            stacked = F.SequenceMask(stacked, valid_length,
                                     use_sequence_length=True, axis=0)
            outputs = [stacked[i] for i in range(length)]
            # per-sequence final state = state at its own last valid step
            # (reference: unroll uses F.SequenceLast over the stacked states)
            states = []
            for s_idx in range(len(all_states[0])):
                s_seq = F.stack(*[st[s_idx] for st in all_states], axis=0)
                states.append(F.SequenceLast(s_seq, valid_length,
                                             use_sequence_length=True, axis=0))
        if merge_outputs:
            t_axis = layout.find("T")
            outputs = F.stack(*outputs, axis=t_axis)
        return outputs, states

    def forward(self, inputs, states=None):
        if states is None:
            states = self.begin_state(inputs.shape[0], ctx=inputs.context,
                                      dtype=str(inputs.dtype))
        return super().forward(inputs, states)


HybridRecurrentCell = RecurrentCell


class RNNCell(RecurrentCell):
    def __init__(self, hidden_size, activation="tanh", input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(hidden_size,), init=i2h_bias_initializer)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(hidden_size,), init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def _infer_param_shapes(self, x, *rest):
        self.i2h_weight._finish_deferred_init((self._hidden_size, x.shape[-1]))
        self.h2h_weight._finish_deferred_init(
            (self._hidden_size, self._hidden_size))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        output = F.Activation(i2h + h2h, act_type=self._activation)
        return output, [output]


class LSTMCell(RecurrentCell):
    """reference: rnn_cell.py::LSTMCell — gates i, f, g(c~), o."""

    def __init__(self, hidden_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(4 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(4 * hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(4 * hidden_size,), init=i2h_bias_initializer)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(4 * hidden_size,), init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def _infer_param_shapes(self, x, *rest):
        self.i2h_weight._finish_deferred_init(
            (4 * self._hidden_size, x.shape[-1]))
        self.h2h_weight._finish_deferred_init(
            (4 * self._hidden_size, self._hidden_size))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        in_gate, forget_gate, in_trans, out_gate = F.split(
            gates, num_outputs=4, axis=1)
        in_gate = F.sigmoid(in_gate)
        forget_gate = F.sigmoid(forget_gate)
        in_trans = F.Activation(in_trans, act_type="tanh")
        out_gate = F.sigmoid(out_gate)
        next_c = forget_gate * states[1] + in_gate * in_trans
        next_h = out_gate * F.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(RecurrentCell):
    """reference: rnn_cell.py::GRUCell — gates r, z, n."""

    def __init__(self, hidden_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(3 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(3 * hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(3 * hidden_size,), init=i2h_bias_initializer)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(3 * hidden_size,), init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def _infer_param_shapes(self, x, *rest):
        self.i2h_weight._finish_deferred_init(
            (3 * self._hidden_size, x.shape[-1]))
        self.h2h_weight._finish_deferred_init(
            (3 * self._hidden_size, self._hidden_size))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size)
        h2h = F.FullyConnected(prev_h, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size)
        i2h_r, i2h_z, i2h_n = F.split(i2h, num_outputs=3, axis=1)
        h2h_r, h2h_z, h2h_n = F.split(h2h, num_outputs=3, axis=1)
        reset = F.sigmoid(i2h_r + h2h_r)
        update = F.sigmoid(i2h_z + h2h_z)
        next_n = F.Activation(i2h_n + reset * h2h_n, act_type="tanh")
        next_h = (1.0 - update) * next_n + update * prev_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        infos = []
        for cell in self._children.values():
            infos.extend(cell.state_info(batch_size))
        return infos

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def hybrid_forward(self, F, inputs, states):
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            state = states[p : p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states


class DropoutCell(RecurrentCell):
    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states


class ModifierCell(RecurrentCell):
    def __init__(self, base_cell):
        super().__init__(prefix=base_cell.prefix + self._alias() + "_",
                         params=None)
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(batch_size, func=func, **kwargs)
        self.base_cell._modified = True
        return begin


class ZoneoutCell(ModifierCell):
    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        self._alias_name = "zoneout"
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super().reset()
        self._prev_output = None

    def hybrid_forward(self, F, inputs, states):
        next_output, next_states = self.base_cell(inputs, states)
        mask = lambda p, like: F.Dropout(F.ones_like(like), p=p, mode="always")
        prev_output = self._prev_output if self._prev_output is not None \
            else F.zeros_like(next_output)
        if self.zoneout_outputs > 0.0:
            output = F.where(mask(self.zoneout_outputs, next_output) != 0,
                             next_output, prev_output)
        else:
            output = next_output
        if self.zoneout_states > 0.0:
            new_states = [F.where(mask(self.zoneout_states, ns) != 0, ns, os)
                          for ns, os in zip(next_states, states)]
        else:
            new_states = next_states
        self._prev_output = output
        return output, new_states


class ResidualCell(ModifierCell):
    def _alias(self):
        return "residual"

    def hybrid_forward(self, F, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states


class BidirectionalCell(RecurrentCell):
    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")
        self._output_prefix = output_prefix

    def state_info(self, batch_size=0):
        return (self._children["l_cell"].state_info(batch_size)
                + self._children["r_cell"].state_info(batch_size))

    def __call__(self, inputs, states=None):
        raise MXNetError("BidirectionalCell supports only unroll()")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as F

        self.reset()
        in_list, axis, batch = _format_sequence(length, inputs, layout, False)
        l_cell = self._children["l_cell"]
        r_cell = self._children["r_cell"]
        if begin_state is None:
            ctx = in_list[0].context
            begin_state = self.begin_state(batch, ctx=ctx,
                                           dtype=str(in_list[0].dtype))
        n_l = len(l_cell.state_info(batch))
        cell_layout = "TNC" if axis == 0 else "NTC"
        l_outputs, l_states = l_cell.unroll(
            length, in_list, begin_state[:n_l], layout=cell_layout,
            merge_outputs=False, valid_length=valid_length)
        if valid_length is None:
            rev_in = list(reversed(in_list))
        else:
            # length-aware reverse so padding stays at the tail
            # (reference: F.SequenceReverse(..., sequence_length=valid_length))
            stacked = F.stack(*in_list, axis=0)
            rev = F.SequenceReverse(stacked, valid_length,
                                    use_sequence_length=True, axis=0)
            rev_in = [rev[i] for i in range(length)]
        r_outputs, r_states = r_cell.unroll(
            length, rev_in, begin_state[n_l:], layout=cell_layout,
            merge_outputs=False, valid_length=valid_length)
        if valid_length is None:
            r_outputs = list(reversed(r_outputs))
        else:
            stacked = F.stack(*r_outputs, axis=0)
            rev = F.SequenceReverse(stacked, valid_length,
                                    use_sequence_length=True, axis=0)
            r_outputs = [rev[i] for i in range(length)]
        outputs = [F.concat(lo, ro, dim=1)
                   for lo, ro in zip(l_outputs, r_outputs)]
        if merge_outputs:
            t_axis = layout.find("T")
            outputs = F.stack(*outputs, axis=t_axis)
        return outputs, l_states + r_states


class HybridSequentialRNNCell(SequentialRNNCell):
    """Hybridizable sequential cell container (reference: rnn_cell.py ::
    HybridSequentialRNNCell — identical semantics here, where every cell
    container is already trace/jit-compatible)."""
