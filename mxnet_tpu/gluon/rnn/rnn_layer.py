"""Fused RNN layers.

Reference: ``python/mxnet/gluon/rnn/rnn_layer.py`` — `_RNNLayer` base
(weight naming `{l,r}{i}_{i2h,h2h}_{weight,bias}`, layout TNC/NTC,
begin_state) and RNN / LSTM / GRU.
"""
from __future__ import annotations

from ... import ndarray as nd
from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout, bidirectional,
                 input_size, i2h_weight_initializer, h2h_weight_initializer,
                 i2h_bias_initializer, h2h_bias_initializer, mode, gates,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        assert layout in ("TNC", "NTC"), "layout must be TNC or NTC"
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._gates = gates
        ng, ni, nh = gates, input_size, hidden_size
        with self.name_scope():
            for i in range(num_layers):
                for j in ["l", "r"][: self._dir]:
                    self._register_param(
                        f"{j}{i}_i2h_weight", (ng * nh, ni if i == 0 else nh * self._dir),
                        i2h_weight_initializer)
                    self._register_param(
                        f"{j}{i}_h2h_weight", (ng * nh, nh), h2h_weight_initializer)
                    self._register_param(
                        f"{j}{i}_i2h_bias", (ng * nh,), i2h_bias_initializer)
                    self._register_param(
                        f"{j}{i}_h2h_bias", (ng * nh,), h2h_bias_initializer)

    def _register_param(self, name, shape, init):
        p = self.params.get(name, shape=shape, init=init,
                            allow_deferred_init=True)
        self._reg_params[name] = p
        setattr(self, name, p)

    def _infer_param_shapes(self, x, *rest):
        ni = x.shape[2] if self._layout == "TNC" else x.shape[2]
        ng, nh = self._gates, self._hidden_size
        for i in range(self._num_layers):
            in_size = ni if i == 0 else nh * self._dir
            for j in ["l", "r"][: self._dir]:
                getattr(self, f"{j}{i}_i2h_weight")._finish_deferred_init(
                    (ng * nh, in_size))
                getattr(self, f"{j}{i}_h2h_weight")._finish_deferred_init(
                    (ng * nh, nh))
                getattr(self, f"{j}{i}_i2h_bias")._finish_deferred_init((ng * nh,))
                getattr(self, f"{j}{i}_h2h_bias")._finish_deferred_init((ng * nh,))

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, ctx=None, **kwargs):
        """Initial recurrent state (reference: _RNNLayer.begin_state)."""
        from ... import ndarray as F

        func = func or F.zeros
        states = []
        for info in self.state_info(batch_size):
            states.append(func(shape=info["shape"], ctx=ctx, **kwargs))
        return states

    def hybrid_forward(self, F, inputs, states=None, **params):
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, 0, 1)
        batch_size = inputs.shape[1]
        explicit_states = states is not None
        if states is None:
            states = self.begin_state(batch_size, ctx=inputs.context,
                                      dtype=str(inputs.dtype))
        if not isinstance(states, (list, tuple)):
            states = [states]
        flat = self._pack_params(F, params)
        if self._mode == "lstm":
            out, h_n, c_n = F.RNN(inputs, flat, states[0], states[1],
                                  state_size=self._hidden_size,
                                  num_layers=self._num_layers, mode=self._mode,
                                  bidirectional=self._dir == 2, p=self._dropout)
            new_states = [h_n, c_n]
        else:
            out, h_n = F.RNN(inputs, flat, states[0],
                             state_size=self._hidden_size,
                             num_layers=self._num_layers, mode=self._mode,
                             bidirectional=self._dir == 2, p=self._dropout)
            new_states = [h_n]
        if self._layout == "NTC":
            out = F.swapaxes(out, 0, 1)
        if explicit_states:
            return out, new_states
        return out

    def _pack_params(self, F, params):
        """Pack per-layer weights into the fused flat vector (layout matches
        ops/rnn.py::_slice_params)."""
        ws = []
        for i in range(self._num_layers):
            for j in ["l", "r"][: self._dir]:
                ws.append(params[f"{j}{i}_i2h_weight"].reshape(-1))
                ws.append(params[f"{j}{i}_h2h_weight"].reshape(-1))
        for i in range(self._num_layers):
            for j in ["l", "r"][: self._dir]:
                ws.append(params[f"{j}{i}_i2h_bias"])
                ws.append(params[f"{j}{i}_h2h_bias"])
        return F.concat(*ws, dim=0)

    def __repr__(self):
        return (f"{type(self).__name__}({self._hidden_size}, "
                f"num_layers={self._num_layers}, layout={self._layout}, "
                f"bidirectional={self._dir == 2})")


class RNN(_RNNLayer):
    """Vanilla RNN (reference: rnn_layer.py::RNN)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "rnn_" + activation, 1, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "lstm", 4, **kwargs)

    def state_info(self, batch_size=0):
        shape = (self._num_layers * self._dir, batch_size, self._hidden_size)
        return [{"shape": shape, "__layout__": "LNC"},
                {"shape": shape, "__layout__": "LNC"}]


class GRU(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "gru", 3, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
