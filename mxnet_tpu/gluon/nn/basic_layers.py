"""Basic neural-network layers.

Reference: ``python/mxnet/gluon/nn/basic_layers.py`` — Sequential,
HybridSequential, Dense, Dropout, BatchNorm, InstanceNorm, LayerNorm,
GroupNorm, Embedding, Flatten, Lambda, HybridLambda.
"""
from __future__ import annotations

from ... import autograd
from ...base import MXNetError
from ..block import Block, HybridBlock
from ..parameter import DeferredInitializationError

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "BatchNorm",
           "InstanceNorm", "LayerNorm", "GroupNorm", "Embedding", "Flatten",
           "Lambda", "HybridLambda", "Identity"]


class Sequential(Block):
    """Stack of blocks (reference: basic_layers.py::Sequential)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers[key])
            return net
        return layers[key]

    def __iter__(self):
        return iter(self._children.values())


class HybridSequential(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers[key])
            return net
        return layers[key]

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer (reference: basic_layers.py::Dense)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        self._in_units = in_units
        self._flatten = flatten
        self._activation = activation
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), init=weight_initializer,
                dtype=dtype, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), init=bias_initializer, dtype=dtype)
            else:
                self.bias = None
            self.act = _make_activation(activation, self)

    def _infer_param_shapes(self, x, *rest):
        in_units = 1
        if self._flatten:
            for d in x.shape[1:]:
                in_units *= d
        else:
            in_units = x.shape[-1]
        self.weight._finish_deferred_init((self._units, in_units))

    def hybrid_forward(self, F, x, weight, bias=None):
        if bias is not None and self._activation == "gelu":
            from ...pallas_kernels.fused_layers import fused_layers_enabled

            if fused_layers_enabled():
                # bias+GELU epilogue fused into one Pallas VMEM pass when
                # the op's shape/platform gates hold (eager-identical
                # composition otherwise) — the matmul keeps its own
                # dispatch, only the epilogue moves
                out = F.FullyConnected(x, weight, None,
                                       num_hidden=self._units,
                                       no_bias=True, flatten=self._flatten)
                return F._contrib_fused_bias_gelu(out, bias)
        out = F.FullyConnected(x, weight, bias, num_hidden=self._units,
                               no_bias=bias is None, flatten=self._flatten)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        return f"Dense({self._units}, in_units={self.weight.shape[1] if self.weight.shape else '?'})"


def _make_activation(activation, parent):
    if activation is None:
        return None
    from .activations import Activation

    with parent.name_scope():
        act = Activation(activation)
    parent.register_child(act, "act")
    return act


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = tuple(axes)

    def hybrid_forward(self, F, x):
        if self._rate == 0:
            return x
        return F.Dropout(x, p=self._rate, axes=self._axes)

    def __repr__(self):
        return f"Dropout(p = {self._rate}, axes={self._axes})"


class _DefaultAxis(int):
    """Signature-default axis marker (see conv_layers._DefaultLayout)."""


_DEFAULT_BN_AXIS = _DefaultAxis(1)


class BatchNorm(HybridBlock):
    """Batch normalization with functional moving-stat updates
    (reference: basic_layers.py::BatchNorm + src/operator/nn/batch_norm.cc).

    Moving statistics are Parameters with grad_req='null'; in training the
    op returns batch stats and this block folds them into the running
    buffers — in-place in eager mode, via the mutation log when traced.
    """

    def __init__(self, axis=_DEFAULT_BN_AXIS, momentum=0.9, epsilon=1e-5,
                 center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if isinstance(axis, _DefaultAxis):
            # under conv_layout("NHWC") the DEFAULT channel axis moves
            # last; an explicitly passed axis=1 is kept (round-3 advisor
            # finding — same sentinel rule as conv_layers._DefaultLayout)
            from .conv_layers import _layout_override

            axis = -1 if _layout_override[0] == "channels_last" else 1
        self._axis = int(axis)
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        self._in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)
            self.running_mean = self.params.get(
                "running_mean", grad_req="null", shape=(in_channels,),
                init=running_mean_initializer, allow_deferred_init=True,
                differentiable=False)
            self.running_var = self.params.get(
                "running_var", grad_req="null", shape=(in_channels,),
                init=running_variance_initializer, allow_deferred_init=True,
                differentiable=False)

    def _infer_param_shapes(self, x, *rest):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p._finish_deferred_init((c,))

    def cast(self, dtype):
        if str(dtype) in ("float16", "bfloat16"):
            dtype = "float32"  # norm stats stay fp32 (reference: BN fp32 accum)
        super().cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        ret = F.BatchNorm(
            x, gamma, beta, running_mean, running_var,
            eps=self._epsilon, momentum=self._momentum,
            fix_gamma=not self._scale, use_global_stats=self._use_global_stats,
            axis=self._axis)
        if isinstance(ret, (list, tuple)):
            out, mean, var = ret
            m = self._momentum
            with autograd.pause():
                new_mean = running_mean * m + mean.astype(str(running_mean.dtype)) * (1 - m)
                new_var = running_var * m + var.astype(str(running_var.dtype)) * (1 - m)
                running_mean._set_data(new_mean.data)
                running_var._set_data(new_var.data)
            return out
        return ret

    def __repr__(self):
        return f"BatchNorm(axis={self._axis}, momentum={self._momentum}, eps={self._epsilon})"


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def _infer_param_shapes(self, x, *rest):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta):
            p._finish_deferred_init((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._epsilon)


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def _infer_param_shapes(self, x, *rest):
        c = x.shape[1]
        for p in (self.gamma, self.beta):
            p._finish_deferred_init((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)


class GroupNorm(HybridBlock):
    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_groups = num_groups
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def _infer_param_shapes(self, x, *rest):
        c = x.shape[1]
        for p in (self.gamma, self.beta):
            p._finish_deferred_init((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.GroupNorm(x, gamma, beta, num_groups=self._num_groups,
                           eps=self._epsilon)


class Embedding(HybridBlock):
    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._sparse_grad = bool(sparse_grad)
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim),
                init=weight_initializer, dtype=dtype,
                grad_stype="row_sparse" if sparse_grad else "default")

    def hybrid_forward(self, F, x, weight):
        if self._sparse_grad:
            # uid = the weight parameter's full name: the train step maps
            # scope-log entries back to optimizer slots by it
            return F.Embedding(x, weight, input_dim=self._input_dim,
                               output_dim=self._output_dim,
                               sparse_grad=True,
                               _sparse_uid=self.weight.name)
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim)

    def __repr__(self):
        return f"Embedding({self._input_dim} -> {self._output_dim})"


class Flatten(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.Flatten(x)

    def __repr__(self):
        return "Flatten"


class Identity(HybridBlock):
    def hybrid_forward(self, F, x):
        return x


class Lambda(Block):
    """Wrap a function as a Block (reference: basic_layers.py::Lambda)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd_mod

            self._func = getattr(nd_mod, function)
            self._func_name = function
        else:
            self._func = function
            self._func_name = getattr(function, "__name__", "custom")

    def forward(self, *args):
        return self._func(*args)

    def __repr__(self):
        return f"Lambda({self._func_name})"


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            self._func_name = function

            def _f(F, *args):
                return getattr(F, function)(*args)

            self._func = _f
        else:
            self._func = lambda F, *args: function(F, *args)
            self._func_name = getattr(function, "__name__", "custom")

    def hybrid_forward(self, F, *args):
        return self._func(F, *args)

    def __repr__(self):
        return f"HybridLambda({self._func_name})"
