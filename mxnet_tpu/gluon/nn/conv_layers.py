"""Convolution and pooling layers.

Reference: ``python/mxnet/gluon/nn/conv_layers.py`` — `_Conv` base,
Conv1D/2D/3D (+Transpose), Max/Avg pools 1/2/3D, Global pools,
ReflectionPad2D.

TPU layout note: MXNet's API default is channels-first (NCHW), but the
TPU conv emitters want channels-last (the lane dimension is the channel
dimension — NCHW convs compile with activation relayouts on both sides).
``conv_layout("NHWC")`` switches the *default* layout of every
conv/pool/BatchNorm block constructed inside the context, so a whole model
can be built channels-last with one line while weights stay OIHW
(checkpoints are layout-independent). See PERF.md round 3.
"""
from __future__ import annotations

import contextlib

from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
           "Conv3DTranspose", "MaxPool1D", "MaxPool2D", "MaxPool3D",
           "AvgPool1D", "AvgPool2D", "AvgPool3D", "GlobalMaxPool1D",
           "GlobalMaxPool2D", "GlobalMaxPool3D", "GlobalAvgPool1D",
           "GlobalAvgPool2D", "GlobalAvgPool3D", "ReflectionPad2D",
           "conv_layout", "current_conv_layout"]

_CHANNELS_LAST = {1: "NWC", 2: "NHWC", 3: "NDHWC"}
_CHANNELS_FIRST = {1: "NCW", 2: "NCHW", 3: "NCDHW"}
_layout_override = [None]  # "channels_last" | "channels_first" | None


class _DefaultLayout(str):
    """Signature-default layout marker: compares/prints as the plain
    string, but lets ``conv_layout`` distinguish "caller kept the
    default" from "caller explicitly asked for channels-first" — an
    explicit ``layout='NCHW'`` inside ``conv_layout('NHWC')`` is kept
    (round-3 advisor finding: it used to be silently flipped)."""


_NCW = _DefaultLayout("NCW")
_NCHW = _DefaultLayout("NCHW")
_NCDHW = _DefaultLayout("NCDHW")


@contextlib.contextmanager
def conv_layout(layout):
    """Build-time default-layout context: ``with conv_layout("NHWC"): ...``.

    Inside the context every conv/pool/BatchNorm block whose caller did not
    choose a non-default layout is constructed channels-last ("NCHW" etc.
    restores channels-first). Affects block CONSTRUCTION only — a built
    block's layout is fixed.
    """
    mode = "channels_last" if layout.endswith("C") else "channels_first"
    prev = _layout_override[0]
    _layout_override[0] = mode
    try:
        yield
    finally:
        _layout_override[0] = prev


def current_conv_layout(ndim=2):
    """The layout a conv/pool block built right now would default to."""
    if _layout_override[0] == "channels_last":
        return _CHANNELS_LAST[ndim]
    return _CHANNELS_FIRST[ndim]


def _resolve_layout(layout, ndim):
    """Apply the conv_layout override to a block's layout argument.

    The override only replaces SIGNATURE-DEFAULT layouts (the
    ``_DefaultLayout`` sentinels): any layout the caller passed
    explicitly — channels-first included — is kept.
    """
    if _layout_override[0] == "channels_last" \
            and isinstance(layout, _DefaultLayout):
        return _CHANNELS_LAST[ndim]
    return str(layout)


def _tup(val, n):
    if isinstance(val, int):
        return (val,) * n
    return tuple(val)


class _Conv(HybridBlock):
    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 op_name="Convolution", adj=None, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._channels = channels
        self._in_channels = in_channels
        layout = _resolve_layout(layout, len(kernel_size))
        self._layout = layout
        ndim = len(kernel_size)
        self._kwargs = {
            "kernel": kernel_size, "stride": strides, "dilate": dilation,
            "pad": padding, "num_filter": channels, "num_group": groups,
            "layout": layout,
        }
        self._op_name = op_name
        if adj is not None:
            self._kwargs["adj"] = adj
        with self.name_scope():
            if op_name == "Convolution":
                wshape = (channels, in_channels // groups if in_channels else 0) + tuple(kernel_size)
            else:  # Deconvolution: weight is (in, out//groups, *k)
                wshape = (in_channels, channels // groups) + tuple(kernel_size)
            self.weight = self.params.get(
                "weight", shape=wshape, init=weight_initializer,
                allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(channels,), init=bias_initializer)
            else:
                self.bias = None
            from .basic_layers import _make_activation

            self.act = _make_activation(activation, self)

    def _infer_param_shapes(self, x, *rest):
        in_c = x.shape[-1 if (self._layout or "").endswith("C") else 1]
        w = list(self.weight.shape)
        if self._op_name == "Convolution":
            w[1] = in_c // self._kwargs["num_group"]
        else:
            w[0] = in_c
        self.weight._finish_deferred_init(tuple(w))

    def hybrid_forward(self, F, x, weight, bias=None):
        op = getattr(F, self._op_name)
        out = op(x, weight, bias, no_bias=bias is None, **self._kwargs)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        return (f"{type(self).__name__}({self._channels}, "
                f"kernel_size={self._kwargs['kernel']}, "
                f"stride={self._kwargs['stride']})")


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, dilation=1,
                 groups=1, layout=_NCW, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _tup(kernel_size, 1), _tup(strides, 1),
                         _tup(padding, 1), _tup(dilation, 1), groups, layout,
                         in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout=_NCHW, activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tup(kernel_size, 2), _tup(strides, 2),
                         _tup(padding, 2), _tup(dilation, 2), groups, layout,
                         in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout=_NCDHW, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _tup(kernel_size, 3), _tup(strides, 3),
                         _tup(padding, 3), _tup(dilation, 3), groups, layout,
                         in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout=_NCW,
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tup(kernel_size, 1), _tup(strides, 1),
                         _tup(padding, 1), _tup(dilation, 1), groups, layout,
                         in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name="Deconvolution", adj=_tup(output_padding, 1),
                         **kwargs)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout=_NCHW, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _tup(kernel_size, 2), _tup(strides, 2),
                         _tup(padding, 2), _tup(dilation, 2), groups, layout,
                         in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name="Deconvolution", adj=_tup(output_padding, 2),
                         **kwargs)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout=_NCDHW, activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tup(kernel_size, 3), _tup(strides, 3),
                         _tup(padding, 3), _tup(dilation, 3), groups, layout,
                         in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name="Deconvolution", adj=_tup(output_padding, 3),
                         **kwargs)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, ceil_mode, global_pool,
                 pool_type, layout=None, count_include_pad=None, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        if strides is None:
            strides = pool_size
        layout = _resolve_layout(layout, len(pool_size))
        self._kwargs = {
            "kernel": pool_size, "stride": strides, "pad": padding,
            "pool_type": pool_type, "global_pool": global_pool,
            "pooling_convention": "full" if ceil_mode else "valid",
            "layout": layout,
        }
        if count_include_pad is not None:
            self._kwargs["count_include_pad"] = count_include_pad

    def hybrid_forward(self, F, x):
        return F.Pooling(x, **self._kwargs)

    def __repr__(self):
        return (f"{type(self).__name__}(size={self._kwargs['kernel']}, "
                f"stride={self._kwargs['stride']}, "
                f"padding={self._kwargs['pad']})")


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout=_NCW,
                 ceil_mode=False, **kwargs):
        super().__init__(_tup(pool_size, 1), _tup(strides, 1) if strides is not None else None,
                         _tup(padding, 1), ceil_mode, False, "max",
                         layout=layout, **kwargs)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout=_NCHW, ceil_mode=False, **kwargs):
        super().__init__(_tup(pool_size, 2), _tup(strides, 2) if strides is not None else None,
                         _tup(padding, 2), ceil_mode, False, "max",
                         layout=layout, **kwargs)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout=_NCDHW, ceil_mode=False, **kwargs):
        super().__init__(_tup(pool_size, 3), _tup(strides, 3) if strides is not None else None,
                         _tup(padding, 3), ceil_mode, False, "max",
                         layout=layout, **kwargs)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout=_NCW,
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_tup(pool_size, 1), _tup(strides, 1) if strides is not None else None,
                         _tup(padding, 1), ceil_mode, False, "avg",
                         layout=layout,
                         count_include_pad=count_include_pad, **kwargs)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout=_NCHW, ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(_tup(pool_size, 2), _tup(strides, 2) if strides is not None else None,
                         _tup(padding, 2), ceil_mode, False, "avg",
                         layout=layout,
                         count_include_pad=count_include_pad, **kwargs)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout=_NCDHW, ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(_tup(pool_size, 3), _tup(strides, 3) if strides is not None else None,
                         _tup(padding, 3), ceil_mode, False, "avg",
                         layout=layout,
                         count_include_pad=count_include_pad, **kwargs)


class _GlobalPool(_Pooling):
    def __init__(self, ndim, pool_type, layout, **kwargs):
        super().__init__((1,) * ndim, (1,) * ndim, (0,) * ndim, False, True,
                         pool_type, layout=layout, **kwargs)


class GlobalMaxPool1D(_GlobalPool):
    def __init__(self, layout=_NCW, **kwargs):
        super().__init__(1, "max", layout, **kwargs)


class GlobalMaxPool2D(_GlobalPool):
    def __init__(self, layout=_NCHW, **kwargs):
        super().__init__(2, "max", layout, **kwargs)


class GlobalMaxPool3D(_GlobalPool):
    def __init__(self, layout=_NCDHW, **kwargs):
        super().__init__(3, "max", layout, **kwargs)


class GlobalAvgPool1D(_GlobalPool):
    def __init__(self, layout=_NCW, **kwargs):
        super().__init__(1, "avg", layout, **kwargs)


class GlobalAvgPool2D(_GlobalPool):
    def __init__(self, layout=_NCHW, **kwargs):
        super().__init__(2, "avg", layout, **kwargs)


class GlobalAvgPool3D(_GlobalPool):
    def __init__(self, layout=_NCDHW, **kwargs):
        super().__init__(3, "avg", layout, **kwargs)


class ReflectionPad2D(HybridBlock):
    def __init__(self, padding=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if isinstance(padding, int):
            padding = (0, 0, 0, 0, padding, padding, padding, padding)
        self._padding = tuple(padding)

    def hybrid_forward(self, F, x):
        return F.Pad(x, mode="reflect", pad_width=self._padding)
