"""Loss blocks.

Reference: ``python/mxnet/gluon/loss.py`` — the Loss base (weight +
batch_axis + sample weighting via `_apply_weighting`), and the zoo:
L1Loss, L2Loss, SoftmaxCrossEntropyLoss, SigmoidBinaryCrossEntropyLoss,
KLDivLoss, HuberLoss, HingeLoss, SquaredHingeLoss, LogisticLoss,
TripletLoss, CTCLoss, CosineEmbeddingLoss, PoissonNLLLoss.
"""
from __future__ import annotations

from .block import HybridBlock

__all__ = ["Loss", "L1Loss", "L2Loss", "SoftmaxCrossEntropyLoss",
           "SoftmaxCELoss", "SigmoidBinaryCrossEntropyLoss", "SigmoidBCELoss",
           "KLDivLoss", "HuberLoss", "HingeLoss", "SquaredHingeLoss",
           "LogisticLoss", "TripletLoss", "CTCLoss", "CosineEmbeddingLoss",
           "PoissonNLLLoss"]


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    if sample_weight is not None:
        loss = F.broadcast_mul(loss, sample_weight)
    if weight is not None:
        loss = loss * weight
    return loss


def _reshape_like(F, x, y):
    return F.reshape_like(x, y)


class Loss(HybridBlock):
    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        return f"{self.__class__.__name__}(batch_axis={self._batch_axis}, w={self._weight})"

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    def _mean_over_nonbatch(self, F, loss):
        ax = self._batch_axis
        axes = tuple(i for i in range(loss.ndim) if i != ax)
        if not axes:
            return loss
        return F.mean(loss, axis=axes)


class L2Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(label - pred)
        loss = _apply_weighting(F, loss, self._weight / 2, sample_weight)
        return self._mean_over_nonbatch(F, loss)


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_over_nonbatch(F, loss)


class SigmoidBinaryCrossEntropyLoss(Loss):
    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None, pos_weight=None):
        label = _reshape_like(F, label, pred)
        if not self._from_sigmoid:
            # log-sum-exp stable BCE on logits
            if pos_weight is None:
                loss = F.relu(pred) - pred * label + F.Activation(
                    -F.abs(pred), act_type="softrelu")
            else:
                log_weight = 1 + F.broadcast_mul(pos_weight - 1, label)
                loss = pred - pred * label + log_weight * (
                    F.Activation(-F.abs(pred), act_type="softrelu")
                    + F.relu(-pred))
        else:
            eps = 1e-12
            if pos_weight is None:
                loss = -(F.log(pred + eps) * label
                         + F.log(1.0 - pred + eps) * (1.0 - label))
            else:
                loss = -(F.broadcast_mul(F.log(pred + eps) * label, pos_weight)
                         + F.log(1.0 - pred + eps) * (1.0 - label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_over_nonbatch(F, loss)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """reference: gluon/loss.py::SoftmaxCrossEntropyLoss — fused
    log-softmax + pick; sparse_label switches one-hot vs dense label."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if self._sparse_label and not self._from_logits:
            # fused sparse CE: lse(pred) - pred[label]. Unlike
            # log_softmax+pick this never materialises the normalised
            # (N, vocab) matrix — the exp/convert fuse into the reduction
            # loops, which is the difference between ~1 GB of HBM traffic
            # and none on an MLM head (N=B*L, vocab~30k) per step.
            # Reductions and the pick gather read the logits in their
            # INPUT dtype: a shared up-front f32 cast would have to be
            # materialised as a full (N, vocab) f32 buffer because the
            # gather can't fuse through it (measured 2.3 ms / 1 GB on
            # BERT-base, PERF.md round 3). The f32 converts below fuse
            # into the reduction loops; subtraction and accumulation stay
            # exact f32.
            m = F.max(pred, axis=self._axis, keepdims=True)
            m32 = F.cast(m, "float32")
            lse = F.log(F.sum(F.exp(F.cast(pred, "float32") - m32),
                              axis=self._axis, keepdims=True)) + m32
            loss = lse - F.cast(F.pick(pred, label, axis=self._axis,
                                       keepdims=True), "float32")
        else:
            if not self._from_logits:
                pred = F.log_softmax(pred, axis=self._axis)
            if self._sparse_label:
                loss = -F.pick(pred, label, axis=self._axis, keepdims=True)
            else:
                label = _reshape_like(F, label, pred)
                loss = -F.sum(pred * label, axis=self._axis, keepdims=True)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_over_nonbatch(F, loss)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        loss = label * (F.log(label + 1e-12) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_over_nonbatch(F, loss)


class HuberLoss(Loss):
    def __init__(self, rho=1.0, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = F.where(loss > self._rho,
                       loss - 0.5 * self._rho,
                       (0.5 / self._rho) * F.square(loss))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_over_nonbatch(F, loss)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.relu(self._margin - pred * label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_over_nonbatch(F, loss)


class SquaredHingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(F.relu(self._margin - pred * label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_over_nonbatch(F, loss)


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._label_format = label_format

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        loss = F.relu(pred) - pred * label + F.Activation(
            -F.abs(pred), act_type="softrelu")
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_over_nonbatch(F, loss)


class TripletLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative, sample_weight=None):
        positive = _reshape_like(F, positive, pred)
        negative = _reshape_like(F, negative, pred)
        loss = F.sum(F.square(positive - pred) - F.square(negative - pred),
                     axis=self._batch_axis, exclude=True)
        loss = F.relu(loss + self._margin)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class CTCLoss(Loss):
    """reference: gluon/loss.py::CTCLoss (layouts TNC/NTC)."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None, **kwargs):
        assert layout in ("NTC", "TNC")
        assert label_layout in ("NT", "TN")
        self._layout = layout
        self._label_layout = label_layout
        super().__init__(weight, label_layout.find("N"), **kwargs)

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        if self._layout == "NTC":
            pred = F.swapaxes(pred, 0, 1)
        if self._batch_axis == 1:
            label = F.swapaxes(label, 0, 1)
        loss = F.CTCLoss(pred, label, pred_lengths, label_lengths,
                         use_data_lengths=pred_lengths is not None,
                         use_label_lengths=label_lengths is not None,
                         blank_label="last")
        return _apply_weighting(F, loss, self._weight, sample_weight)


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, input1, input2, label, sample_weight=None):
        eps = 1e-12
        dot = F.sum(input1 * input2, axis=-1)
        n1 = F.sqrt(F.sum(F.square(input1), axis=-1) + eps)
        n2 = F.sqrt(F.sum(F.square(input2), axis=-1) + eps)
        cos = dot / (n1 * n2)
        label = label.reshape(cos.shape)
        loss = F.where(label == 1, 1.0 - cos,
                       F.relu(cos - self._margin))
        return _apply_weighting(F, loss, self._weight, sample_weight)


class PoissonNLLLoss(Loss):
    def __init__(self, weight=None, from_logits=True, batch_axis=0,
                 compute_full=False, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def hybrid_forward(self, F, pred, target, sample_weight=None, epsilon=1e-08):
        target = _reshape_like(F, target, pred)
        if self._from_logits:
            loss = F.exp(pred) - target * pred
        else:
            loss = pred - target * F.log(pred + epsilon)
        if self._compute_full:
            stirling = (target * F.log(target + epsilon) - target
                        + 0.5 * F.log(2 * 3.141592653589793 * (target + epsilon)))
            stirling = F.where(target <= 1, F.zeros_like(target), stirling)
            loss = loss + stirling
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss)
