"""Datasets.

Reference: ``python/mxnet/gluon/data/dataset.py`` — Dataset, SimpleDataset,
ArrayDataset, RecordFileDataset, and the lazy transform wrappers behind
``Dataset.transform`` / ``transform_first``.
"""
from __future__ import annotations

from typing import Callable

from ...base import MXNetError

__all__ = ["Dataset", "SimpleDataset", "ArrayDataset", "RecordFileDataset"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def transform(self, fn: Callable, lazy: bool = True):
        trans = _LazyTransformDataset(self, fn)
        if lazy:
            return trans
        return SimpleDataset([trans[i] for i in range(len(trans))])

    def transform_first(self, fn: Callable, lazy: bool = True):
        return self.transform(_TransformFirstClosure(fn), lazy)


class SimpleDataset(Dataset):
    def __init__(self, data):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class _LazyTransformDataset(Dataset):
    def __init__(self, data, fn):
        self._data = data
        self._fn = fn

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        item = self._data[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class _TransformFirstClosure:
    def __init__(self, fn):
        self._fn = fn

    def __call__(self, x, *args):
        if args:
            return (self._fn(x),) + args
        return self._fn(x)


class ArrayDataset(Dataset):
    """Zip of array-likes (reference: dataset.py::ArrayDataset)."""

    def __init__(self, *args):
        assert len(args) > 0
        self._length = len(args[0])
        self._data = []
        for i, data in enumerate(args):
            assert len(data) == self._length, \
                f"All arrays must have the same length; arg {i} has " \
                f"{len(data)} while the first has {self._length}"
            if isinstance(data, (list, tuple)):
                data = SimpleDataset(list(data))
            self._data.append(data)

    def __len__(self):
        return self._length

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(d[idx] for d in self._data)


class RecordFileDataset(Dataset):
    """Dataset over a RecordIO file (reference: dataset.py::RecordFileDataset;
    the reader is the native recordio module)."""

    def __init__(self, filename):
        from ... import recordio

        self._filename = filename
        idx_file = filename[: filename.rfind(".")] + ".idx"
        self._record = recordio.MXIndexedRecordIO(idx_file, filename, "r")

    def __getitem__(self, idx):
        return self._record.read_idx(self._record.keys[idx])

    def __len__(self):
        return len(self._record.keys)
