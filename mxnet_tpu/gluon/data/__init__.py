"""Gluon data API (reference: python/mxnet/gluon/data/)."""
from . import vision  # noqa: F401
from .dataloader import DataLoader, default_batchify_fn  # noqa: F401
from .dataset import ArrayDataset, Dataset, RecordFileDataset, SimpleDataset  # noqa: F401
from .sampler import BatchSampler, RandomSampler, Sampler, SequentialSampler  # noqa: F401
