"""DataLoader — the host input pipeline.

Reference: ``python/mxnet/gluon/data/dataloader.py :: DataLoader`` —
multiprocessing workers + POSIX-shm NDArray rebuild
(``src/storage/cpu_shared_storage_manager.h``), `default_batchify_fn`,
`pin_memory`, thread_pool mode, prefetch.

TPU-native design: workers produce **numpy** batches on the host (the
TPU analogue of cpu_shared memory — host staging buffers); the final
``device_put`` happens when the consumer moves the batch to its context
(`batch.as_in_context(mx.tpu())`), which XLA overlaps with compute.

Process-worker transport (``MXNET_TPU_FORK_WORKERS=1``) is ZERO-COPY
over POSIX shared memory, mirroring the reference's
``cpu_shared_storage_manager.h`` rebuild: the worker batchifies (stacks)
sample trees into ``multiprocessing.shared_memory`` blocks and sends
only (name, shape, dtype) descriptors through the pickle channel; the
parent maps each block and wraps it without copying the payload through
the pipe. Opt out with ``MXNET_TPU_SHM=0`` (falls back to pickled
numpy); a custom ``batchify_fn`` also falls back, since worker-side
stacking implements the DEFAULT batchify only — same constraint as the
reference's ``default_mp_batchify_fn``. Thread-pool mode (the default)
shares an address space and needs no transport at all. A prefetch queue
of ``2*num_workers`` batches keeps the device fed.

``pin_memory=True`` stages each yielded batch onto
``jax.devices()[pin_device_id]`` with an async ``device_put`` (see
``_pin``); for mesh-sharded async prefetch onto a TrainStep's input
layout, wrap the loader in ``io.DeviceFeedIter``.
"""
from __future__ import annotations

import multiprocessing
import os
import sys
import threading
import queue as _queue
from typing import Callable, Optional

import numpy as _np

from ...base import MXNetError
from ...ndarray import NDArray, array as nd_array
from .dataset import Dataset
from .sampler import BatchSampler, RandomSampler, Sampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn", "default_mp_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference: dataloader.py::default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        import jax.numpy as jnp

        return nd_array(_np.stack([d.asnumpy() for d in data]))
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(list(i)) for i in data]
    arr = _np.asarray(data)
    return nd_array(arr)


default_mp_batchify_fn = default_batchify_fn


def _as_numpy_sample(sample):
    if isinstance(sample, NDArray):
        return sample.asnumpy()
    if isinstance(sample, tuple):
        return tuple(_as_numpy_sample(s) for s in sample)
    return sample


_worker_dataset = None


def _worker_initializer(dataset):
    global _worker_dataset
    _worker_dataset = dataset


def _stack_tree(samples):
    """default-batchify a list of numpy sample trees into batch arrays."""
    first = samples[0]
    if isinstance(first, tuple):
        return tuple(_stack_tree([s[i] for s in samples])
                     for i in range(len(first)))
    return _np.stack([_np.asarray(s) for s in samples])


def _alloc_shm(shape, dtype, name=None):
    """Create one worker-side shm block to fill in place.

    Returns ``(descriptor, view, done)``: write the payload into ``view``
    then call ``done()`` — it drops the worker's mapping and unregisters
    the block from the worker-side resource tracker (the PARENT owns the
    unlink; double-unlink at worker exit would race the consumer).
    Decode workers fill samples straight into the block, skipping the
    stack-then-copy intermediate ``_to_shm`` pays. ``name`` lets the
    parent pre-assign the block name, so blocks whose descriptor never
    arrives (worker timeout, pool terminate) remain sweepable by prefix
    (``ImageIter.close``)."""
    from multiprocessing import shared_memory

    dt = _np.dtype(dtype)
    nbytes = int(_np.prod(shape)) * dt.itemsize
    shm = shared_memory.SharedMemory(name=name, create=True,
                                     size=max(nbytes, 1))
    view = _np.ndarray(shape, dt, buffer=shm.buf)
    name = shm.name

    def done():
        shm.close()
        try:
            from multiprocessing import resource_tracker

            resource_tracker.unregister(
                shm._name if hasattr(shm, "_name") else "/" + name,
                "shared_memory")
        except Exception:
            pass

    return (("__shm__", name, tuple(int(s) for s in shape), str(dt)),
            view, done)


def _to_shm(tree):
    """Copy batch arrays into shm blocks; return descriptor tree."""
    if isinstance(tree, tuple):
        return tuple(_to_shm(t) for t in tree)
    arr = _np.ascontiguousarray(tree)
    desc, view, done = _alloc_shm(arr.shape, arr.dtype)
    view[...] = arr
    done()
    return desc


def _unlink_shm(tree):
    """Best-effort unlink of every block in a descriptor tree — cleanup
    path for batches that were prefetched but never consumed."""
    from multiprocessing import shared_memory

    if isinstance(tree, tuple) and len(tree) == 4 and tree[0] == "__shm__":
        try:
            shm = shared_memory.SharedMemory(name=tree[1])
            shm.close()
            shm.unlink()
        except Exception:
            pass
        return
    if isinstance(tree, tuple):
        for t in tree:
            _unlink_shm(t)


def _from_shm_numpy(tree):
    """Map a descriptor tree back into HOST numpy arrays; unlink the
    blocks. The numpy-only rebuild exists for consumers that must stay
    off the device (``image.ImageIter``'s decode workers assemble numpy
    batches; wrapping into NDArrays here would device_put every chunk)."""
    from multiprocessing import shared_memory

    if isinstance(tree, tuple) and len(tree) == 4 and tree[0] == "__shm__":
        _, name, shape, dtype = tree
        shm = shared_memory.SharedMemory(name=name)
        view = _np.ndarray(shape, dtype, buffer=shm.buf)
        # explicit memcpy out of the block BEFORE unmapping: the CPU
        # backend may zero-copy-alias a numpy buffer, and unmapping under
        # a live alias segfaults. The IPC hop itself stayed descriptor-
        # only; this is the one host copy the reference's shm rebuild
        # also pays (NDArray over shm -> consumer copy on first write).
        arr = view.copy()
        shm.close()
        shm.unlink()
        return arr
    if isinstance(tree, tuple):
        return [_from_shm_numpy(t) for t in tree]
    return tree


def _from_shm(tree):
    """Map descriptor tree back into NDArrays; unlink the blocks."""
    if isinstance(tree, tuple) and len(tree) == 4 and tree[0] == "__shm__":
        return nd_array(_from_shm_numpy(tree))
    if isinstance(tree, tuple):
        return [_from_shm(t) for t in tree]
    return tree


def _from_shm_into(desc, out, ofs=0):
    """Copy one block's payload straight into ``out[ofs:ofs+n]`` (one
    memcpy, no intermediate array) and unlink it; returns n. The batch-
    assembly fast path for consumers that own a preallocated buffer
    (``image.ImageIter``'s decode chunks)."""
    from multiprocessing import shared_memory

    _, name, shape, dtype = desc
    shm = shared_memory.SharedMemory(name=name)
    view = _np.ndarray(shape, dtype, buffer=shm.buf)
    n = shape[0]
    out[ofs:ofs + n] = view
    shm.close()
    shm.unlink()
    return n


def _worker_fn(samples, batchify_is_default, use_shm=False):
    """Runs in a worker process: fetch + transform samples; either return
    pickled numpy samples, or (shm mode) batchify here and ship only
    shared-memory descriptors."""
    global _worker_dataset
    out = [_as_numpy_sample(_worker_dataset[i]) for i in samples]
    if use_shm and batchify_is_default:
        return _to_shm(_stack_tree(out))
    return out


class DataLoader:
    """Mini-batch loader over a Dataset (reference: dataloader.py::DataLoader)."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, pin_device_id=0,
                 prefetch=None, thread_pool=False, timeout=120):
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._pin_device_id = pin_device_id
        self._thread_pool = thread_pool
        self._timeout = timeout
        if batch_sampler is None:
            if batch_size is None:
                raise MXNetError(
                    "batch_size must be specified unless batch_sampler is")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise MXNetError("shuffle must be False with a custom sampler")
            batch_sampler = BatchSampler(sampler, batch_size, last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None
              or last_batch is not None):
            raise MXNetError(
                "batch_size/shuffle/sampler/last_batch must not be given "
                "with a batch_sampler")
        self._batch_sampler = batch_sampler
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._batchify_is_default = batchify_fn is None
        self._use_shm = (self._batchify_is_default
                         and os.environ.get("MXNET_TPU_SHM", "1") != "0")
        self._pool = None
        if self._num_workers > 0:
            # Worker transport: thread pool by default. fork() after JAX
            # initialization can deadlock (JAX is multithreaded), and jax ops
            # release the GIL, so threads give the same overlap the
            # reference gets from processes+shm without the fork hazard.
            # Real process workers are opt-in via MXNET_TPU_FORK_WORKERS=1.
            if not thread_pool and os.environ.get("MXNET_TPU_FORK_WORKERS"):
                ctx = multiprocessing.get_context("fork")
                self._pool = ctx.Pool(
                    self._num_workers, initializer=_worker_initializer,
                    initargs=(dataset,))
            else:
                from multiprocessing.pool import ThreadPool

                self._thread_pool = True
                self._pool = ThreadPool(self._num_workers)

    def __len__(self):
        return len(self._batch_sampler)

    def __iter__(self):
        if self._pool is None:
            for batch_idx in self._batch_sampler:
                yield self._batchify([self._dataset[i] for i in batch_idx])
            return
        # async path: schedule `prefetch` batches ahead through the pool
        pending = _queue.Queue()
        it = iter(self._batch_sampler)

        def submit():
            try:
                batch_idx = next(it)
            except StopIteration:
                return False
            if self._thread_pool:
                res = self._pool.apply_async(
                    lambda idx: [_as_numpy_sample(self._dataset[i]) for i in idx],
                    (batch_idx,))
            else:
                res = self._pool.apply_async(
                    _worker_fn, (batch_idx, self._batchify_is_default,
                                 self._use_shm))
            pending.put(res)
            return True

        shm_mode = (not self._thread_pool and self._use_shm
                    and self._batchify_is_default)
        for _ in range(self._prefetch or 1):
            if not submit():
                break
        current = [None]  # the popped-but-unconsumed result, for cleanup
        try:
            while not pending.empty():
                res = pending.get()
                current[0] = res
                samples = res.get(self._timeout)
                current[0] = None
                submit()
                if shm_mode:
                    batch = _from_shm(samples)  # stacked in the worker
                    # structure matches default_batchify_fn exactly: a
                    # tuple sample (ANY arity) -> list of arrays, a bare
                    # array sample -> one array
                    if self._pin_memory:
                        batch = _pin(batch, self._pin_device_id)
                    yield batch
                else:
                    yield self._batchify(samples)
        finally:
            # early break / generator close / worker error / timeout: the
            # workers unregistered their blocks from the resource tracker,
            # so the parent must unlink every prefetched-but-unconsumed
            # batch (including the one whose get() just failed) or
            # /dev/shm fills across runs
            if shm_mode:
                leftovers = [current[0]] if current[0] is not None else []
                while not pending.empty():
                    leftovers.append(pending.get())
                for res in leftovers:
                    # short re-wait only: a result whose get() already
                    # timed out will not become ready now, and re-waiting
                    # the full timeout per leftover would stall generator
                    # teardown by minutes on a single stuck worker
                    try:
                        _unlink_shm(res.get(1.0))
                    except Exception:
                        pass

    def _batchify(self, samples):
        batch = self._batchify_fn(samples)
        if self._pin_memory:
            batch = _pin(batch, self._pin_device_id)
        return batch

    def __del__(self):
        pool = getattr(self, "_pool", None)
        if pool is not None:
            try:
                pool.terminate()
            except Exception:
                pass  # interpreter shutdown: pool internals may be gone


def _pin(batch, device_id=0):
    """``pin_memory`` routed through the device-feed staging path: the
    batch payloads are ``device_put`` onto ``jax.devices()[device_id]``
    (async — the H2D copy overlaps the consumer's compute), the TPU
    analogue of the reference's pinned-host staging buffers. For sharded
    multi-device placement wrap the loader in ``io.DeviceFeedIter``
    instead, which also prefetches ahead."""
    from ...io.device_feed import stage_on_device

    return stage_on_device(batch, device_id)
