"""DataLoader — the host input pipeline.

Reference: ``python/mxnet/gluon/data/dataloader.py :: DataLoader`` —
multiprocessing workers + POSIX-shm NDArray rebuild
(``src/storage/cpu_shared_storage_manager.h``), `default_batchify_fn`,
`pin_memory`, thread_pool mode, prefetch.

TPU-native design: workers produce **numpy** batches on the host (the
TPU analogue of cpu_shared memory — host staging buffers); the final
``device_put`` happens when the consumer moves the batch to its context
(`batch.as_in_context(mx.tpu())`), which XLA overlaps with compute.
Worker transport uses multiprocessing with pickled numpy (zero-copy shm is
an optimization slot; the API contract is identical). A prefetch queue of
``2*num_workers`` batches keeps the device fed.
"""
from __future__ import annotations

import multiprocessing
import os
import sys
import threading
import queue as _queue
from typing import Callable, Optional

import numpy as _np

from ...base import MXNetError
from ...context import cpu_pinned
from ...ndarray import NDArray, array as nd_array
from .dataset import Dataset
from .sampler import BatchSampler, RandomSampler, Sampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn", "default_mp_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference: dataloader.py::default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        import jax.numpy as jnp

        return nd_array(_np.stack([d.asnumpy() for d in data]))
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(list(i)) for i in data]
    arr = _np.asarray(data)
    return nd_array(arr)


default_mp_batchify_fn = default_batchify_fn


def _as_numpy_sample(sample):
    if isinstance(sample, NDArray):
        return sample.asnumpy()
    if isinstance(sample, tuple):
        return tuple(_as_numpy_sample(s) for s in sample)
    return sample


_worker_dataset = None


def _worker_initializer(dataset):
    global _worker_dataset
    _worker_dataset = dataset


def _worker_fn(samples, batchify_is_default):
    """Runs in a worker process: fetch + transform samples, return numpy."""
    global _worker_dataset
    out = [_as_numpy_sample(_worker_dataset[i]) for i in samples]
    return out


class DataLoader:
    """Mini-batch loader over a Dataset (reference: dataloader.py::DataLoader)."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, pin_device_id=0,
                 prefetch=None, thread_pool=False, timeout=120):
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._thread_pool = thread_pool
        self._timeout = timeout
        if batch_sampler is None:
            if batch_size is None:
                raise MXNetError(
                    "batch_size must be specified unless batch_sampler is")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise MXNetError("shuffle must be False with a custom sampler")
            batch_sampler = BatchSampler(sampler, batch_size, last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None
              or last_batch is not None):
            raise MXNetError(
                "batch_size/shuffle/sampler/last_batch must not be given "
                "with a batch_sampler")
        self._batch_sampler = batch_sampler
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._pool = None
        if self._num_workers > 0:
            # Worker transport: thread pool by default. fork() after JAX
            # initialization can deadlock (JAX is multithreaded), and jax ops
            # release the GIL, so threads give the same overlap the
            # reference gets from processes+shm without the fork hazard.
            # Real process workers are opt-in via MXNET_TPU_FORK_WORKERS=1.
            if not thread_pool and os.environ.get("MXNET_TPU_FORK_WORKERS"):
                ctx = multiprocessing.get_context("fork")
                self._pool = ctx.Pool(
                    self._num_workers, initializer=_worker_initializer,
                    initargs=(dataset,))
            else:
                from multiprocessing.pool import ThreadPool

                self._thread_pool = True
                self._pool = ThreadPool(self._num_workers)

    def __len__(self):
        return len(self._batch_sampler)

    def __iter__(self):
        if self._pool is None:
            for batch_idx in self._batch_sampler:
                yield self._batchify([self._dataset[i] for i in batch_idx])
            return
        # async path: schedule `prefetch` batches ahead through the pool
        pending = _queue.Queue()
        it = iter(self._batch_sampler)

        def submit():
            try:
                batch_idx = next(it)
            except StopIteration:
                return False
            if self._thread_pool:
                res = self._pool.apply_async(
                    lambda idx: [_as_numpy_sample(self._dataset[i]) for i in idx],
                    (batch_idx,))
            else:
                res = self._pool.apply_async(_worker_fn, (batch_idx, True))
            pending.put(res)
            return True

        for _ in range(self._prefetch or 1):
            if not submit():
                break
        while not pending.empty():
            res = pending.get()
            samples = res.get(self._timeout)
            submit()
            yield self._batchify(samples)

    def _batchify(self, samples):
        batch = self._batchify_fn(samples)
        if self._pin_memory:
            batch = _pin(batch)
        return batch

    def __del__(self):
        pool = getattr(self, "_pool", None)
        if pool is not None:
            try:
                pool.terminate()
            except Exception:
                pass  # interpreter shutdown: pool internals may be gone


def _pin(batch):
    if isinstance(batch, (list, tuple)):
        return [_pin(b) for b in batch]
    return batch.as_in_context(cpu_pinned())
