"""Vision transforms.

Reference: ``python/mxnet/gluon/data/vision/transforms.py`` — Compose,
Cast, ToTensor, Normalize, Resize, CenterCrop, RandomResizedCrop,
RandomFlipLeftRight/TopBottom, ColorJitter et al.

These run in the host input pipeline (DataLoader workers) on HWC uint8
NDArrays, exactly like the reference's cv2/mshadow augmenters — keeping
the device free for training compute.
"""
from __future__ import annotations

import math
import random as _pyrandom

import numpy as _np

from ....ndarray import NDArray, array as nd_array
from ...block import Block, HybridBlock
from ...nn.basic_layers import HybridSequential

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomResizedCrop", "RandomFlipLeftRight", "RandomFlipTopBottom",
           "RandomBrightness", "RandomContrast", "RandomSaturation",
           "RandomHue", "RandomColorJitter", "RandomLighting", "CropResize"]


def _to_np(img):
    return img.asnumpy() if isinstance(img, NDArray) else _np.asarray(img)


class Compose(HybridSequential):
    """Chain transforms (reference: transforms.py::Compose)."""

    def __init__(self, transforms):
        super().__init__()
        with self.name_scope():
            for t in transforms:
                self.add(t)

    def hybrid_forward(self, F, x):
        for block in self._children.values():
            x = block(x)
        return x


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return x.astype(self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (reference: ToTensor)."""

    def hybrid_forward(self, F, x):
        if x.ndim == 3:
            return F.transpose(x.astype("float32"), axes=(2, 0, 1)) / 255.0
        return F.transpose(x.astype("float32"), axes=(0, 3, 1, 2)) / 255.0


class Normalize(HybridBlock):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = _np.asarray(mean, dtype="float32").reshape(-1, 1, 1)
        self._std = _np.asarray(std, dtype="float32").reshape(-1, 1, 1)

    def hybrid_forward(self, F, x):
        mean = nd_array(self._mean, ctx=x.context)
        std = nd_array(self._std, ctx=x.context)
        return F.broadcast_div(F.broadcast_sub(x, mean), std)


def _resize_np(img, w, h):
    """Bilinear resize on host numpy (the cv2 role)."""
    src = _to_np(img).astype("float32")
    if src.ndim == 2:
        src = src[:, :, None]
    sh, sw, c = src.shape
    ys = _np.linspace(0, sh - 1, h)
    xs = _np.linspace(0, sw - 1, w)
    y0 = _np.floor(ys).astype(int)
    x0 = _np.floor(xs).astype(int)
    y1 = _np.minimum(y0 + 1, sh - 1)
    x1 = _np.minimum(x0 + 1, sw - 1)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    out = (src[y0][:, x0] * (1 - wy) * (1 - wx)
           + src[y0][:, x1] * (1 - wy) * wx
           + src[y1][:, x0] * wy * (1 - wx)
           + src[y1][:, x1] * wy * wx)
    return out


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (tuple, list)) else (size, size)
        self._keep = keep_ratio

    def forward(self, x):
        w, h = self._size
        if self._keep:
            sh, sw = x.shape[:2]
            scale = min(w / sw, h / sh)
            w, h = int(sw * scale), int(sh * scale)
        out = _resize_np(x, w, h)
        return nd_array(out.astype("uint8") if _to_np(x).dtype == _np.uint8 else out)


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (tuple, list)) else (size, size)

    def forward(self, x):
        w, h = self._size
        sh, sw = x.shape[:2]
        if sh < h or sw < w:
            out = _resize_np(x, max(w, sw), max(h, sh))
            x = nd_array(out)
            sh, sw = x.shape[:2]
        y0 = (sh - h) // 2
        x0 = (sw - w) // 2
        return x[y0 : y0 + h, x0 : x0 + w]


class CropResize(Block):
    def __init__(self, x, y, width, height, size=None, interpolation=1):
        super().__init__()
        self._x, self._y, self._w, self._h = x, y, width, height
        self._size = size

    def forward(self, img):
        out = img[self._y : self._y + self._h, self._x : self._x + self._w]
        if self._size:
            w, h = self._size if isinstance(self._size, (tuple, list)) \
                else (self._size, self._size)
            out = nd_array(_resize_np(out, w, h))
        return out


class RandomResizedCrop(Block):
    """reference: transforms.py::RandomResizedCrop — random area/ratio crop
    then resize."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (tuple, list)) else (size, size)
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        h, w = x.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = _pyrandom.uniform(*self._scale) * area
            log_ratio = (math.log(self._ratio[0]), math.log(self._ratio[1]))
            aspect = math.exp(_pyrandom.uniform(*log_ratio))
            cw = int(round(math.sqrt(target_area * aspect)))
            ch = int(round(math.sqrt(target_area / aspect)))
            if cw <= w and ch <= h:
                x0 = _pyrandom.randint(0, w - cw)
                y0 = _pyrandom.randint(0, h - ch)
                crop = x[y0 : y0 + ch, x0 : x0 + cw]
                return nd_array(_resize_np(crop, *self._size).astype("uint8"))
        return CenterCrop(self._size).forward(x)


class RandomFlipLeftRight(Block):
    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        if _pyrandom.random() < self._p:
            return x.flip(axis=1)
        return x


class RandomFlipTopBottom(Block):
    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        if _pyrandom.random() < self._p:
            return x.flip(axis=0)
        return x


class _RandomJitter(Block):
    def _factor(self, spread):
        return 1.0 + _pyrandom.uniform(-spread, spread)


class RandomBrightness(_RandomJitter):
    def __init__(self, brightness):
        super().__init__()
        self._b = brightness

    def forward(self, x):
        f = self._factor(self._b)
        out = _np.clip(_to_np(x).astype("float32") * f, 0, 255)
        return nd_array(out.astype(_to_np(x).dtype))


class RandomContrast(_RandomJitter):
    def __init__(self, contrast):
        super().__init__()
        self._c = contrast

    def forward(self, x):
        f = self._factor(self._c)
        src = _to_np(x).astype("float32")
        mean = src.mean()
        out = _np.clip((src - mean) * f + mean, 0, 255)
        return nd_array(out.astype(_to_np(x).dtype))


class RandomSaturation(_RandomJitter):
    def __init__(self, saturation):
        super().__init__()
        self._s = saturation

    def forward(self, x):
        f = self._factor(self._s)
        src = _to_np(x).astype("float32")
        gray = src.mean(axis=-1, keepdims=True)
        out = _np.clip(gray + (src - gray) * f, 0, 255)
        return nd_array(out.astype(_to_np(x).dtype))


class RandomHue(_RandomJitter):
    def __init__(self, hue):
        super().__init__()
        self._h = hue

    def forward(self, x):
        # lightweight hue rotation in YIQ space (reference uses HSV via cv2)
        f = _pyrandom.uniform(-self._h, self._h) * math.pi
        src = _to_np(x).astype("float32") / 255.0
        t_yiq = _np.array([[0.299, 0.587, 0.114],
                           [0.596, -0.274, -0.321],
                           [0.211, -0.523, 0.311]], dtype="float32")
        t_rgb = _np.linalg.inv(t_yiq)
        yiq = src @ t_yiq.T
        c, s = math.cos(f), math.sin(f)
        rot = _np.array([[1, 0, 0], [0, c, -s], [0, s, c]], dtype="float32")
        out = _np.clip((yiq @ rot.T) @ t_rgb.T, 0, 1) * 255
        return nd_array(out.astype(_to_np(x).dtype))


class RandomColorJitter(Block):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))
        if hue:
            self._ts.append(RandomHue(hue))

    def forward(self, x):
        ts = list(self._ts)
        _pyrandom.shuffle(ts)
        for t in ts:
            x = t.forward(x)
        return x


class RandomLighting(Block):
    """AlexNet-style PCA lighting noise (reference: RandomLighting)."""

    _EIGVAL = _np.array([55.46, 4.794, 1.148], dtype="float32")
    _EIGVEC = _np.array([[-0.5675, 0.7192, 0.4009],
                         [-0.5808, -0.0045, -0.814],
                         [-0.5836, -0.6948, 0.4203]], dtype="float32")

    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        a = _np.random.normal(0, self._alpha, size=(3,)).astype("float32")
        delta = (self._EIGVEC * a * self._EIGVAL).sum(axis=1)
        out = _np.clip(_to_np(x).astype("float32") + delta, 0, 255)
        return nd_array(out.astype(_to_np(x).dtype))
