"""Vision datasets.

Reference: ``python/mxnet/gluon/data/vision/datasets.py`` — MNIST,
FashionMNIST, CIFAR10, CIFAR100, ImageRecordDataset, ImageFolderDataset.

This environment has zero network egress, so downloads are impossible.
Each dataset first looks for the standard files under ``root``; if absent
it falls back to a **deterministic synthetic surrogate** with the same
shapes/dtypes and *learnable* class structure (each class is a fixed random
prototype plus noise), so convergence tests (SURVEY.md §4 tier
"small-training") remain meaningful. ``synthetic`` attribute reports which
mode is active.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile
from typing import Optional

import numpy as _np

from ..dataset import ArrayDataset, Dataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset"]


def _synthetic_images(num, shape, num_classes, seed, proto_seed=None):
    """Class-prototype + noise images: linearly separable enough to learn,
    hard enough that an untrained net is at chance.

    ``proto_seed`` (default: ``seed``) draws the class prototypes and MUST
    be shared across a dataset's train/test splits — with per-split
    prototypes a model trained on one split is at chance on the other
    (the bug this parameter fixes: train/test "MNIST" surrogates used to
    describe different classes entirely).
    """
    protos = _np.random.RandomState(
        seed if proto_seed is None else proto_seed).uniform(
        0, 255, size=(num_classes,) + shape).astype("float32")
    # disjoint stream for labels/noise: seeding with `seed` directly would
    # replay the prototype RNG's draws when seed == proto_seed, making
    # train-split noise a function of the prototype pixels
    rng = _np.random.RandomState(seed + 100003)
    labels = rng.randint(0, num_classes, size=(num,)).astype("int32")
    noise = rng.normal(0, 64.0, size=(num,) + shape).astype("float32")
    imgs = _np.clip(protos[labels] * 0.6 + noise, 0, 255).astype("uint8")
    return imgs, labels


class _DownloadedDataset(Dataset):
    def __init__(self, root, transform):
        self._root = os.path.expanduser(root)
        self._transform = transform
        self._data = None
        self._label = None
        self.synthetic = False
        self._get_data()

    def __getitem__(self, idx):
        from ....ndarray import array as nd_array

        img = nd_array(self._data[idx], dtype="uint8")
        label = self._label[idx]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self._label)


class MNIST(_DownloadedDataset):
    """MNIST (reference: datasets.py::MNIST). Shape (28, 28, 1) uint8."""

    _NUM_CLASSES = 10
    _SHAPE = (28, 28, 1)
    _SEED = 42

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None):
        self._train = train
        super().__init__(root, transform)

    def _get_data(self):
        if self._train:
            data_file = os.path.join(self._root, "train-images-idx3-ubyte.gz")
            label_file = os.path.join(self._root, "train-labels-idx1-ubyte.gz")
            n = 60000
        else:
            data_file = os.path.join(self._root, "t10k-images-idx3-ubyte.gz")
            label_file = os.path.join(self._root, "t10k-labels-idx1-ubyte.gz")
            n = 10000
        if os.path.exists(data_file) and os.path.exists(label_file):
            with gzip.open(label_file, "rb") as fin:
                struct.unpack(">II", fin.read(8))
                label = _np.frombuffer(fin.read(), dtype=_np.uint8).astype(_np.int32)
            with gzip.open(data_file, "rb") as fin:
                struct.unpack(">IIII", fin.read(16))
                data = _np.frombuffer(fin.read(), dtype=_np.uint8)
                data = data.reshape(len(label), 28, 28, 1)
            self._data, self._label = data, label
            return
        # zero-egress fallback: learnable synthetic surrogate
        self.synthetic = True
        n_synth = 8192 if self._train else 2048
        seed = self._SEED if self._train else self._SEED + 1
        self._data, self._label = _synthetic_images(
            n_synth, self._SHAPE, self._NUM_CLASSES, seed,
            proto_seed=self._SEED)


class FashionMNIST(MNIST):
    _SEED = 77

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"), train=True,
                 transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR-10 (reference: datasets.py::CIFAR10). Shape (32, 32, 3) uint8."""

    _NUM_CLASSES = 10
    _SHAPE = (32, 32, 3)
    _SEED = 10

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar10"),
                 train=True, transform=None):
        self._train = train
        super().__init__(root, transform)

    def _read_batch(self, filename):
        with open(filename, "rb") as fin:
            raw = _np.frombuffer(fin.read(), dtype=_np.uint8).reshape(-1, 3072 + 1)
        return (raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1),
                raw[:, 0].astype(_np.int32))

    def _get_data(self):
        batches = [os.path.join(self._root, f"data_batch_{i}.bin")
                   for i in range(1, 6)] if self._train else \
                  [os.path.join(self._root, "test_batch.bin")]
        if all(os.path.exists(b) for b in batches):
            data, label = zip(*[self._read_batch(b) for b in batches])
            self._data = _np.concatenate(data)
            self._label = _np.concatenate(label)
            return
        self.synthetic = True
        n = 8192 if self._train else 2048
        seed = self._SEED if self._train else self._SEED + 1
        self._data, self._label = _synthetic_images(
            n, self._SHAPE, self._NUM_CLASSES, seed,
            proto_seed=self._SEED)


class CIFAR100(CIFAR10):
    _NUM_CLASSES = 100
    _SEED = 100

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar100"),
                 fine_label=False, train=True, transform=None):
        self._fine_label = fine_label
        super().__init__(root, train, transform)

    def _get_data(self):
        f = os.path.join(self._root, "train.bin" if self._train else "test.bin")
        if os.path.exists(f):
            with open(f, "rb") as fin:
                raw = _np.frombuffer(fin.read(), dtype=_np.uint8).reshape(-1, 3072 + 2)
            self._data = raw[:, 2:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
            self._label = raw[:, 1 if self._fine_label else 0].astype(_np.int32)
            return
        self.synthetic = True
        n = 8192 if self._train else 2048
        self._data, self._label = _synthetic_images(
            n, self._SHAPE, self._NUM_CLASSES,
            self._SEED if self._train else self._SEED + 1,
            proto_seed=self._SEED)


class ImageRecordDataset(Dataset):
    """Dataset over an image RecordIO file (reference:
    datasets.py::ImageRecordDataset)."""

    def __init__(self, filename, flag=1, transform=None):
        from ..dataset import RecordFileDataset

        self._record = RecordFileDataset(filename)
        self._flag = flag
        self._transform = transform

    def __len__(self):
        return len(self._record)

    def __getitem__(self, idx):
        from .... import image, recordio
        from ....ndarray import array as nd_array

        raw = self._record[idx]
        header, img_bytes = recordio.unpack(raw)
        img = image.imdecode(img_bytes, flag=self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class ImageFolderDataset(Dataset):
    """Images organized as root/<class>/<img> (reference:
    datasets.py::ImageFolderDataset)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = {".jpg", ".jpeg", ".png", ".npy"}
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                if os.path.splitext(filename)[1].lower() in self._exts:
                    self.items.append((os.path.join(path, filename), label))

    def __len__(self):
        return len(self.items)

    def __getitem__(self, idx):
        from .... import image
        from ....ndarray import array as nd_array

        path, label = self.items[idx]
        if path.endswith(".npy"):
            img = nd_array(_np.load(path))
        else:
            with open(path, "rb") as f:
                img = image.imdecode(f.read(), flag=self._flag)
        if self._transform is not None:
            return self._transform(img, label)
        return img, label
