"""``mx.np`` — the NumPy-semantics frontend (reference:
``python/mxnet/numpy/multiarray.py`` and siblings).

The reference reimplements ~250 NumPy operators in C++ (``_np_*`` kernels)
and wraps them behind an ``mx.np.ndarray`` with NumPy semantics. Here the
compute layer IS NumPy-semantics already (jax.numpy), so the frontend is
thin: every function routes the payloads through ``imperative_invoke`` with
a jnp-backed op so autograd recording, context handling, ``out=``, and the
naive-engine sync contract behave exactly like the ``mx.nd`` layer, and the
result class is rebound to ``mx.np.ndarray`` (same object — tape linkage
preserved).

Scope notes vs the reference: bool-mask and fancy indexing go through the
same tape-aware path as basic indexing; in-place arithmetic mutates
through the NDArray write lens (views write through).
"""
from __future__ import annotations

import builtins
import math as _math

import numpy as _onp

from ..base import MXNetError
from ..context import Context, current_context
from ..ndarray.ndarray import NDArray, imperative_invoke, _LambdaOp

__all__ = ["ndarray"]  # extended programmatically below


def _jnp():
    import jax.numpy as jnp

    return jnp


# ---------------------------------------------------------------------------
# class
# ---------------------------------------------------------------------------


def _np_wrap(res):
    """Rebind results to the np ndarray class IN PLACE (keeps tape nodes)."""
    if isinstance(res, NDArray):
        res.__class__ = ndarray
        return res
    if isinstance(res, (list, tuple)):
        return type(res)(_np_wrap(r) for r in res)
    return res


def _invoke(name, fn, tensors, attrs=None, out=None):
    return _np_wrap(imperative_invoke(_LambdaOp(fn, name), list(tensors),
                                      dict(attrs or {}), out=out))


class ndarray(NDArray):
    """NumPy-semantics array (reference: ``numpy/multiarray.py::ndarray``).

    Subclasses the imperative NDArray: device/context handling, autograd
    (attach_grad/backward), views and serialization are shared; operators
    and methods follow NumPy conventions (true division, operator dtype
    promotion via jnp, tuple axes everywhere).
    """

    def as_nd_ndarray(self):
        out = NDArray(data=self.data, ctx=self._ctx)
        return out

    def as_np_ndarray(self):
        return self

    # -- operators (all tape-aware via imperative_invoke) ---------------
    def _np_binop(self, other, jname, reflected=False):
        jnp = _jnp()
        jf = getattr(jnp, jname)
        fn = (lambda a, b: jf(b, a)) if reflected else jf
        other_t = other if isinstance(other, NDArray) else other
        return _invoke(f"np_{jname}", fn, [self, other_t])

    def __add__(self, other):
        return self._np_binop(other, "add")

    __radd__ = __add__

    def __sub__(self, other):
        return self._np_binop(other, "subtract")

    def __rsub__(self, other):
        return self._np_binop(other, "subtract", reflected=True)

    def __mul__(self, other):
        return self._np_binop(other, "multiply")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._np_binop(other, "true_divide")

    def __rtruediv__(self, other):
        return self._np_binop(other, "true_divide", reflected=True)

    def __floordiv__(self, other):
        return self._np_binop(other, "floor_divide")

    def __rfloordiv__(self, other):
        return self._np_binop(other, "floor_divide", reflected=True)

    def __mod__(self, other):
        return self._np_binop(other, "mod")

    def __rmod__(self, other):
        return self._np_binop(other, "mod", reflected=True)

    def __pow__(self, other):
        return self._np_binop(other, "power")

    def __rpow__(self, other):
        return self._np_binop(other, "power", reflected=True)

    def __matmul__(self, other):
        return self._np_binop(other, "matmul")

    def __rmatmul__(self, other):
        return self._np_binop(other, "matmul", reflected=True)

    def __neg__(self):
        return _invoke("np_negative", _jnp().negative, [self])

    def __abs__(self):
        return _invoke("np_abs", _jnp().abs, [self])

    def __eq__(self, other):
        if other is None:
            return False
        return self._np_binop(other, "equal")

    def __ne__(self, other):
        if other is None:
            return True
        return self._np_binop(other, "not_equal")

    def __lt__(self, other):
        return self._np_binop(other, "less")

    def __le__(self, other):
        return self._np_binop(other, "less_equal")

    def __gt__(self, other):
        return self._np_binop(other, "greater")

    def __ge__(self, other):
        return self._np_binop(other, "greater_equal")

    __hash__ = None  # numpy arrays are unhashable

    def __iadd__(self, other):
        NDArray.__iadd__(self, other)
        return self

    def __isub__(self, other):
        NDArray.__isub__(self, other)
        return self

    # -- indexing -------------------------------------------------------
    def __getitem__(self, key):
        def _is_adv(k):
            return isinstance(k, (NDArray, _onp.ndarray)) or (
                isinstance(k, (list,)) and len(k) > 0
                and not isinstance(k[0], slice))

        advanced = _is_adv(key) or (isinstance(key, tuple)
                                    and builtins.any(_is_adv(k)
                                                     for k in key))
        if not advanced:
            try:
                return _np_wrap(NDArray.__getitem__(self, key))
            except (MXNetError, TypeError, IndexError, NotImplementedError):
                pass
        # advanced indexing (bool masks, fancy integer arrays): tape-aware
        # functional gather. jax silently CASTS a bool index array to an
        # int gather, so masks are converted to nonzero indices on host
        # (they are concrete — this is the eager frontend).
        def _idx(k):
            if isinstance(k, NDArray):
                k = _onp.asarray(k.data) if str(k.data.dtype) == "bool" \
                    else k.data
            if isinstance(k, _onp.ndarray) and k.dtype == _onp.bool_:
                return _onp.nonzero(k)
            return k

        if isinstance(key, tuple):
            parts = [_idx(k) for k in key]
            if builtins.any(isinstance(p, tuple) for p in parts):
                raise MXNetError(
                    "boolean masks inside a tuple index are not supported; "
                    "index with the mask alone or use np.where")
            idx = tuple(parts)
        else:
            idx = _idx(key)
        return _invoke("np_getitem", lambda d: d[idx], [self])

    # -- ndarray protocol ------------------------------------------------
    @property
    def T(self):
        return _invoke("np_transpose", _jnp().transpose, [self])

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        axes = axes or None
        return _invoke("np_transpose",
                       lambda d: _jnp().transpose(d, axes), [self])

    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        order = kwargs.pop("order", "C")
        if kwargs:
            raise TypeError(f"unexpected kwargs {list(kwargs)}")
        if order != "C":
            raise MXNetError("only C-order reshape is supported")
        return _invoke("np_reshape",
                       lambda d: _jnp().reshape(d, shape), [self])

    def astype(self, dtype, copy=True):
        return _np_wrap(NDArray.astype(self, dtype))

    def copy(self):
        return _np_wrap(NDArray.copy(self))

    def item(self, *args):
        return self.asnumpy().item(*args)

    def flatten(self, order="C"):
        return self.reshape(-1)

    def ravel(self):
        return self.reshape(-1)

    @property
    def size(self):
        return int(_onp.prod(self.shape)) if self.shape else 1

    def _reduce(self, jname, axis=None, keepdims=False, **kw):
        jf = getattr(_jnp(), jname)
        return _invoke(
            f"np_{jname}",
            lambda d: jf(d, axis=axis, keepdims=keepdims, **kw), [self])

    def sum(self, axis=None, dtype=None, keepdims=False, **kw):
        out = self._reduce("sum", axis, keepdims)
        return out.astype(dtype) if dtype is not None else out

    def mean(self, axis=None, dtype=None, keepdims=False, **kw):
        out = self._reduce("mean", axis, keepdims)
        return out.astype(dtype) if dtype is not None else out

    def prod(self, axis=None, keepdims=False, **kw):
        return self._reduce("prod", axis, keepdims)

    def max(self, axis=None, keepdims=False, **kw):
        return self._reduce("max", axis, keepdims)

    def min(self, axis=None, keepdims=False, **kw):
        return self._reduce("min", axis, keepdims)

    def std(self, axis=None, ddof=0, keepdims=False, **kw):
        return self._reduce("std", axis, keepdims, ddof=ddof)

    def var(self, axis=None, ddof=0, keepdims=False, **kw):
        return self._reduce("var", axis, keepdims, ddof=ddof)

    def argmax(self, axis=None):
        return _invoke("np_argmax",
                       lambda d: _jnp().argmax(d, axis=axis), [self])

    def argmin(self, axis=None):
        return _invoke("np_argmin",
                       lambda d: _jnp().argmin(d, axis=axis), [self])

    def all(self, axis=None, keepdims=False):
        return self._reduce("all", axis, keepdims)

    def any(self, axis=None, keepdims=False):
        return self._reduce("any", axis, keepdims)

    def cumsum(self, axis=None):
        return _invoke("np_cumsum",
                       lambda d: _jnp().cumsum(d, axis=axis), [self])

    def squeeze(self, axis=None):
        return _invoke("np_squeeze",
                       lambda d: _jnp().squeeze(d, axis=axis), [self])

    def clip(self, a_min=None, a_max=None):
        return _invoke("np_clip",
                       lambda d: _jnp().clip(d, a_min, a_max), [self])

    def round(self, decimals=0):
        return _invoke("np_round",
                       lambda d: _jnp().round(d, decimals), [self])

    def repeat(self, repeats, axis=None):
        return _invoke("np_repeat",
                       lambda d: _jnp().repeat(d, repeats, axis=axis), [self])

    def take(self, indices, axis=None, mode="clip"):
        idx = indices.data if isinstance(indices, NDArray) else indices
        return _invoke("np_take",
                       lambda d: _jnp().take(d, idx, axis=axis,
                                             mode=mode), [self])

    def dot(self, other):
        return self._np_binop(other, "dot")

    def tolist(self):
        return self.asnumpy().tolist()


# ---------------------------------------------------------------------------
# module functions (generated: unary / binary / reduction families)
# ---------------------------------------------------------------------------


def _data(x):
    return x.data if isinstance(x, NDArray) else x


def array(obj, dtype=None, ctx=None):
    import jax

    ctx = ctx or current_context()
    if isinstance(obj, NDArray):
        src = obj.data
        if dtype is not None:
            src = src.astype(dtype)
        return ndarray(data=src, ctx=ctx)
    host = _onp.asarray(obj, dtype=dtype)
    if host.dtype == _onp.float64 and dtype is None:
        host = host.astype(_onp.float32)  # numpy-frontend default dtype
    return ndarray(data=jax.device_put(host, ctx.jax_device()), ctx=ctx)


def _creation(jname):
    def f(shape=None, dtype=None, ctx=None, **kw):
        import jax

        ctx = ctx or current_context()
        jf = getattr(_jnp(), jname)
        with jax.default_device(ctx.jax_device()):
            data = jf(shape, dtype=dtype or "float32", **kw)
        return ndarray(data=data, ctx=ctx)

    f.__name__ = jname
    return f


zeros = _creation("zeros")
ones = _creation("ones")
empty = _creation("empty")


def full(shape, fill_value, dtype=None, ctx=None):
    import jax

    ctx = ctx or current_context()
    with jax.default_device(ctx.jax_device()):
        data = _jnp().full(shape, _data(fill_value), dtype=dtype)
    return ndarray(data=data, ctx=ctx)


def arange(start, stop=None, step=1, dtype=None, ctx=None):
    import jax

    ctx = ctx or current_context()
    with jax.default_device(ctx.jax_device()):
        data = _jnp().arange(start, stop, step, dtype=dtype or "float32")
    return ndarray(data=data, ctx=ctx)


def linspace(start, stop, num=50, endpoint=True, dtype=None, ctx=None, **kw):
    import jax

    ctx = ctx or current_context()
    with jax.default_device(ctx.jax_device()):
        data = _jnp().linspace(start, stop, num, endpoint=endpoint,
                               dtype=dtype or "float32")
    return ndarray(data=data, ctx=ctx)


def eye(N, M=None, k=0, dtype=None, ctx=None):
    import jax

    ctx = ctx or current_context()
    with jax.default_device(ctx.jax_device()):
        data = _jnp().eye(N, M, k=k, dtype=dtype or "float32")
    return ndarray(data=data, ctx=ctx)


def zeros_like(a, dtype=None):
    return _invoke("np_zeros_like",
                   lambda d: _jnp().zeros_like(d, dtype=dtype), [a])


def ones_like(a, dtype=None):
    return _invoke("np_ones_like",
                   lambda d: _jnp().ones_like(d, dtype=dtype), [a])


def full_like(a, fill_value, dtype=None):
    return _invoke("np_full_like",
                   lambda d: _jnp().full_like(d, fill_value, dtype=dtype),
                   [a])


_UNARY = [
    "negative", "absolute", "abs", "exp", "expm1", "log", "log1p", "log2",
    "log10", "sqrt", "cbrt", "square", "reciprocal", "sign", "floor",
    "ceil", "trunc", "rint", "sin", "cos", "tan", "arcsin", "arccos",
    "arctan", "sinh", "cosh", "tanh", "arcsinh", "arccosh", "arctanh",
    "degrees", "radians", "isnan", "isinf", "isfinite", "logical_not",
]
_BINARY = [
    "add", "subtract", "multiply", "divide", "true_divide", "floor_divide",
    "mod", "remainder", "power", "maximum", "minimum", "arctan2", "hypot",
    "matmul", "dot", "equal", "not_equal", "less", "less_equal", "greater",
    "greater_equal", "logical_and", "logical_or", "logical_xor", "copysign",
    "fmod", "outer", "vdot", "inner",
]
_REDUCE = ["sum", "mean", "prod", "std", "var", "amax", "amin", "max",
           "min", "all", "any", "median", "nanmean", "nansum"]


def _def_unary(jname):
    def f(x, out=None, **kw):
        jf = getattr(_jnp(), jname)
        return _invoke(f"np_{jname}", lambda d: jf(d, **kw), [x], out=out)

    f.__name__ = jname
    return f


def _def_binary(jname):
    def f(x1, x2, out=None, **kw):
        jf = getattr(_jnp(), jname)
        return _invoke(f"np_{jname}", lambda a, b: jf(a, b, **kw),
                       [x1, x2], out=out)

    f.__name__ = jname
    return f


def _def_reduce(jname):
    def f(a, axis=None, dtype=None, keepdims=False, out=None, **kw):
        jf = getattr(_jnp(), jname)
        def body(d):
            r = jf(d, axis=axis, keepdims=keepdims, **kw)
            return r.astype(dtype) if dtype is not None else r
        return _invoke(f"np_{jname}", body, [a], out=out)

    f.__name__ = jname
    return f


_g = globals()
for _n in _UNARY:
    _g[_n] = _def_unary(_n)
for _n in _BINARY:
    _g[_n] = _def_binary(_n)
for _n in _REDUCE:
    _g[_n] = _def_reduce(_n)

# numpy's `divide` is true division
divide = _g["true_divide"]


def argmax(a, axis=None, out=None):
    return _invoke("np_argmax", lambda d: _jnp().argmax(d, axis=axis), [a],
                   out=out)


def argmin(a, axis=None, out=None):
    return _invoke("np_argmin", lambda d: _jnp().argmin(d, axis=axis), [a],
                   out=out)


def argsort(a, axis=-1):
    return _invoke("np_argsort", lambda d: _jnp().argsort(d, axis=axis), [a])


def sort(a, axis=-1):
    return _invoke("np_sort", lambda d: _jnp().sort(d, axis=axis), [a])


def cumsum(a, axis=None, dtype=None):
    return _invoke("np_cumsum",
                   lambda d: _jnp().cumsum(d, axis=axis, dtype=dtype), [a])


def clip(a, a_min, a_max, out=None):
    return _invoke("np_clip", lambda d: _jnp().clip(d, a_min, a_max), [a],
                   out=out)


def where(condition, x=None, y=None):
    if x is None and y is None:
        # numpy contract: a TUPLE of per-dimension index arrays
        return _invoke("np_where_cond",
                       lambda c: tuple(_jnp().where(c)), [condition])
    return _invoke("np_where", lambda c, a, b: _jnp().where(c, a, b),
                   [condition, x, y])


def reshape(a, newshape, order="C"):
    return _np_wrap(a.reshape(newshape) if isinstance(a, ndarray)
                    else array(a).reshape(newshape))


def transpose(a, axes=None):
    return _invoke("np_transpose",
                   lambda d: _jnp().transpose(d, axes), [a])


def swapaxes(a, axis1, axis2):
    return _invoke("np_swapaxes",
                   lambda d: _jnp().swapaxes(d, axis1, axis2), [a])


def moveaxis(a, source, destination):
    return _invoke("np_moveaxis",
                   lambda d: _jnp().moveaxis(d, source, destination), [a])


def expand_dims(a, axis):
    return _invoke("np_expand_dims",
                   lambda d: _jnp().expand_dims(d, axis), [a])


def squeeze(a, axis=None):
    return _invoke("np_squeeze", lambda d: _jnp().squeeze(d, axis), [a])


def broadcast_to(a, shape):
    return _invoke("np_broadcast_to",
                   lambda d: _jnp().broadcast_to(d, shape), [a])


def concatenate(seq, axis=0, out=None):
    return _invoke("np_concatenate",
                   lambda *ds: _jnp().concatenate(ds, axis=axis),
                   list(seq), out=out)


def stack(seq, axis=0, out=None):
    return _invoke("np_stack", lambda *ds: _jnp().stack(ds, axis=axis),
                   list(seq), out=out)


def vstack(seq):
    return _invoke("np_vstack", lambda *ds: _jnp().vstack(ds), list(seq))


def hstack(seq):
    return _invoke("np_hstack", lambda *ds: _jnp().hstack(ds), list(seq))


def dstack(seq):
    return _invoke("np_dstack", lambda *ds: _jnp().dstack(ds), list(seq))


def split(ary, indices_or_sections, axis=0):
    sec = indices_or_sections
    if isinstance(sec, (list, tuple)):
        sec = tuple(sec)
    return _invoke("np_split",
                   lambda d: tuple(_jnp().split(d, sec, axis=axis)), [ary])


def array_split(ary, indices_or_sections, axis=0):
    sec = indices_or_sections
    if isinstance(sec, (list, tuple)):
        sec = tuple(sec)
    return _invoke("np_array_split",
                   lambda d: tuple(_jnp().array_split(d, sec, axis=axis)),
                   [ary])


def tile(a, reps):
    return _invoke("np_tile", lambda d: _jnp().tile(d, reps), [a])


def repeat(a, repeats, axis=None):
    return _invoke("np_repeat",
                   lambda d: _jnp().repeat(d, repeats, axis=axis), [a])


def flip(a, axis=None):
    return _invoke("np_flip", lambda d: _jnp().flip(d, axis=axis), [a])


def roll(a, shift, axis=None):
    return _invoke("np_roll",
                   lambda d: _jnp().roll(d, shift, axis=axis), [a])


def take(a, indices, axis=None, mode="clip"):
    idx = _data(indices)
    return _invoke("np_take",
                   lambda d: _jnp().take(d, idx, axis=axis, mode=mode), [a])


def unique(a, return_index=False, return_inverse=False,
           return_counts=False, axis=None):
    res = _onp.unique(a.asnumpy() if isinstance(a, NDArray) else a,
                      return_index=return_index,
                      return_inverse=return_inverse,
                      return_counts=return_counts, axis=axis)
    if isinstance(res, tuple):
        return tuple(array(r) for r in res)
    return array(res)


def tensordot(a, b, axes=2):
    if isinstance(axes, (list, tuple)):
        axes = tuple(tuple(ax) if isinstance(ax, (list, tuple)) else ax
                     for ax in axes)
    return _invoke("np_tensordot",
                   lambda x, y: _jnp().tensordot(x, y, axes=axes), [a, b])


def einsum(subscripts, *operands):
    return _invoke("np_einsum",
                   lambda *ds: _jnp().einsum(subscripts, *ds),
                   list(operands))


def meshgrid(*xi, indexing="xy"):
    return _invoke("np_meshgrid",
                   lambda *ds: tuple(_jnp().meshgrid(*ds,
                                                     indexing=indexing)),
                   list(xi))


def atleast_1d(*arys):
    def one(a):
        a = a if isinstance(a, ndarray) else array(a)
        return a.reshape(-1) if a.ndim == 0 else a

    res = [one(a) for a in arys]
    return res[0] if len(res) == 1 else res


def atleast_2d(*arys):
    """NumPy-semantics atleast_2d (scalars/1-D get leading axes)."""
    def one(a):
        a = a if isinstance(a, ndarray) else array(a)
        if a.ndim == 0:
            return a.reshape(1, 1)
        if a.ndim == 1:
            return expand_dims(a, 0)
        return a

    res = [one(a) for a in arys]
    return res[0] if len(res) == 1 else res


def atleast_3d(*arys):
    """NumPy-semantics atleast_3d (shapes promote to (1,N,1)-style)."""
    def one(a):
        a = a if isinstance(a, ndarray) else array(a)
        if a.ndim == 0:
            return a.reshape(1, 1, 1)
        if a.ndim == 1:
            return a.reshape(1, a.shape[0], 1)
        if a.ndim == 2:
            return expand_dims(a, -1)
        return a

    res = [one(a) for a in arys]
    return res[0] if len(res) == 1 else res


def asarray(obj, dtype=None):
    """array() that is a no-op (no copy) for matching np ndarrays.

    A legacy ``mx.nd`` NDArray is promoted to the np ndarray subclass
    (NumPy semantics were requested), sharing its device buffer.
    """
    if isinstance(obj, ndarray) and (dtype is None
                                     or obj.dtype == _onp.dtype(dtype)):
        return obj
    return array(obj, dtype=dtype)


asanyarray = asarray


def ascontiguousarray(obj, dtype=None):
    # XLA owns physical layout; logical arrays are always C-contiguous
    return asarray(obj, dtype=dtype)


def copyto(dst, src):
    """NumPy copyto: in-place overwrite of dst (tape-transparent write,
    mirroring NDArray's [:] assignment semantics)."""
    if not isinstance(dst, NDArray):
        raise TypeError("np.copyto destination must be an ndarray")
    dst[:] = src if isinstance(src, NDArray) else array(src)


def put(a, ind, v, mode="raise"):
    """NumPy put: flat-index in-place scatter into a (values cycled)."""
    if not isinstance(a, NDArray):
        raise TypeError("np.put target must be an ndarray")
    jnp = _jnp()
    flat = a.data.reshape(-1)
    n = flat.shape[0]
    ind_d = _data(ind) if isinstance(ind, NDArray) else jnp.asarray(
        _onp.asarray(ind))
    ind_d = jnp.asarray(ind_d).reshape(-1)
    v_d = _data(v) if isinstance(v, NDArray) else jnp.asarray(
        _onp.asarray(v))
    v_d = jnp.asarray(v_d).reshape(-1)
    if v_d.size == 0:
        if ind_d.size > 0:  # NumPy: cannot cycle an empty values sequence
            raise ValueError(
                "np.put: cannot put from an empty values array into "
                f"{ind_d.size} indices")
        return
    if v_d.size < ind_d.size:  # NumPy cycles shorter values
        v_d = jnp.tile(v_d, -(-ind_d.size // v_d.size))
    v_d = v_d[:ind_d.size].astype(flat.dtype)
    if mode == "clip":
        ind_d = jnp.clip(ind_d, 0, n - 1)
    elif mode == "wrap":
        ind_d = ind_d % n
    else:  # "raise": jax scatter silently DROPS oob updates — check here
        bad = ((ind_d < -n) | (ind_d >= n)).any()
        if bool(bad):  # eager op: sync is part of the contract
            raise IndexError(
                f"np.put: index out of bounds for size-{n} array")
    a[:] = ndarray(data=flat.at[ind_d].set(v_d).reshape(a.shape))


def place(arr, mask, vals):
    """NumPy place: set arr[mask] from vals cyclically (in-place)."""
    if not isinstance(arr, NDArray):
        raise TypeError("np.place target must be an ndarray")
    host = _onp.array(arr.asnumpy())  # asnumpy may be a read-only view
    _onp.place(host, _onp.asarray(
        mask.asnumpy() if isinstance(mask, NDArray) else mask),
        _onp.asarray(vals.asnumpy() if isinstance(vals, NDArray) else vals,
                     dtype=host.dtype))
    arr[:] = array(host, dtype=arr.dtype)


def putmask(a, mask, values):
    """NumPy putmask: a[mask] = values (broadcast/cycled), in-place."""
    if not isinstance(a, NDArray):
        raise TypeError("np.putmask target must be an ndarray")
    jnp = _jnp()
    m = _data(mask) if isinstance(mask, NDArray) else jnp.asarray(
        _onp.asarray(mask))
    v = _data(values) if isinstance(values, NDArray) else jnp.asarray(
        _onp.asarray(values))
    if v.size == a.size:
        vb = v.reshape(a.shape)
    else:
        reps = -(-a.size // (v.size or 1))  # NB: max/min are np funcs here
        vb = jnp.tile(v.reshape(-1), reps)[:a.size].reshape(a.shape)
    a[:] = ndarray(data=jnp.where(m.astype(bool), vb.astype(a.data.dtype),
                                  a.data))


def put_along_axis(arr, indices, values, axis):
    """NumPy put_along_axis (in-place scatter along an axis)."""
    if not isinstance(arr, NDArray):
        raise TypeError("np.put_along_axis target must be an ndarray")
    jnp = _jnp()
    idx = _data(indices) if isinstance(indices, NDArray) else jnp.asarray(
        _onp.asarray(indices))
    val = _data(values) if isinstance(values, NDArray) else jnp.asarray(
        _onp.asarray(values))
    if axis is None:
        put(arr, idx.reshape(-1), val)
        return
    if hasattr(jnp, "put_along_axis"):
        out = jnp.put_along_axis(arr.data, idx,
                                 jnp.asarray(val).astype(arr.data.dtype),
                                 axis, inplace=False)
    else:  # manual scatter fallback: indices keep THEIR axis extent
        # (NumPy broadcasts indices against values, not against arr)
        bshape = list(arr.shape)
        bshape[axis] = idx.shape[axis]
        midx = jnp.moveaxis(jnp.broadcast_to(idx, bshape), axis, -1)
        mval = jnp.moveaxis(
            jnp.broadcast_to(jnp.asarray(val).astype(arr.data.dtype),
                             bshape), axis, -1)
        moved = jnp.moveaxis(arr.data, axis, -1)
        flatten = moved.reshape(-1, moved.shape[-1])
        fidx = midx.reshape(-1, midx.shape[-1])
        fval = mval.reshape(-1, mval.shape[-1])
        rows = jnp.arange(flatten.shape[0])[:, None]
        out = jnp.moveaxis(
            flatten.at[rows, fidx].set(fval).reshape(moved.shape), -1, axis)
    arr[:] = ndarray(data=out)


def lexsort(keys, axis=-1):
    jnp = _jnp()
    ks = [(_data(k) if isinstance(k, NDArray)
           else jnp.asarray(_onp.asarray(k))) for k in keys]
    return ndarray(data=jnp.lexsort(ks, axis=axis))


def ndenumerate(a):
    a = a if isinstance(a, ndarray) else array(a)
    return _onp.ndenumerate(a.asnumpy())


def ndindex(*shape):
    return _onp.ndindex(*shape)


def isdtype(dtype, kind):
    jnp = _jnp()
    if hasattr(jnp, "isdtype"):
        return jnp.isdtype(dtype, kind)
    return _onp.isdtype(_onp.dtype(dtype), kind)


def from_dlpack(x):
    """Zero-copy import via the DLPack protocol."""
    import jax

    return ndarray(data=jax.numpy.from_dlpack(x))


def may_share_memory(a, b):
    return False


def shape(a):
    return tuple(a.shape)


def ndim(a):
    return len(a.shape) if hasattr(a, "shape") else _onp.ndim(a)


# constants / dtypes (reference: numpy/__init__.py re-exports)
pi = _math.pi
e = _math.e
inf = float("inf")
nan = float("nan")
newaxis = None
euler_gamma = _onp.euler_gamma

float16 = _onp.float16
float32 = _onp.float32
float64 = _onp.float64
int8 = _onp.int8
int16 = _onp.int16
int32 = _onp.int32
int64 = _onp.int64
uint8 = _onp.uint8
bool_ = _onp.bool_
dtype = _onp.dtype


# ---------------------------------------------------------------------------
# linalg / random submodules
# ---------------------------------------------------------------------------


class _Linalg:
    """mx.np.linalg (reference: numpy/linalg.py)."""

    @staticmethod
    def _u(name, *tensors, **kw):
        import jax.numpy.linalg as jla

        jf = getattr(jla, name)
        return _invoke(f"np_linalg_{name}",
                       lambda *ds: jf(*ds, **kw), list(tensors))

    def norm(self, x, ord=None, axis=None, keepdims=False):
        return self._u("norm", x, ord=ord, axis=axis, keepdims=keepdims)

    def inv(self, a):
        return self._u("inv", a)

    def det(self, a):
        return self._u("det", a)

    def slogdet(self, a):
        return self._u("slogdet", a)

    def cholesky(self, a):
        return self._u("cholesky", a)

    def qr(self, a):
        return self._u("qr", a)

    def svd(self, a):
        return self._u("svd", a)

    def eigh(self, a):
        return self._u("eigh", a)

    def solve(self, a, b):
        return self._u("solve", a, b)

    def lstsq(self, a, b, rcond=None):
        return self._u("lstsq", a, b, rcond=rcond)

    def pinv(self, a):
        return self._u("pinv", a)

    def matrix_rank(self, a):
        return self._u("matrix_rank", a)


linalg = _Linalg()


class _Random:
    """mx.np.random (reference: numpy/random.py) — drives the framework's
    counter-based PRNG stream (mx.random.seed applies)."""

    @staticmethod
    def _size(size):
        if size is None:
            return ()
        if isinstance(size, (tuple, list)):
            return tuple(size)
        return (size,)

    @staticmethod
    def _sample(name, sampler, ctx=None):
        # sampling is non-differentiable — draw from the framework stream
        # directly (imperative_invoke only threads rng into registry ops)
        import jax

        from .. import random_state

        ctx = ctx or current_context()
        data = sampler(random_state.next_key())
        return ndarray(data=jax.device_put(data, ctx.jax_device()), ctx=ctx)

    def uniform(self, low=0.0, high=1.0, size=None, dtype=None, ctx=None):
        import jax

        size = self._size(size)
        return self._sample("uniform", lambda rng: jax.random.uniform(
            rng, size, minval=low, maxval=high,
            dtype=dtype or "float32"), ctx)

    def normal(self, loc=0.0, scale=1.0, size=None, dtype=None, ctx=None):
        import jax

        size = self._size(size)
        return self._sample("normal", lambda rng: jax.random.normal(
            rng, size, dtype=dtype or "float32") * scale + loc, ctx)

    def randn(self, *size):
        return self.normal(size=tuple(size) or None)

    def rand(self, *size):
        return self.uniform(size=tuple(size) or None)

    def randint(self, low, high=None, size=None, dtype=None, ctx=None):
        import jax

        if high is None:
            low, high = 0, low
        size = self._size(size)
        return self._sample("randint", lambda rng: jax.random.randint(
            rng, size, low, high, dtype=dtype or "int32"), ctx)

    def choice(self, a, size=None, replace=True, p=None, ctx=None):
        import jax

        size = self._size(size)
        a_val = _data(a) if isinstance(a, NDArray) else a
        pv = _data(p) if isinstance(p, NDArray) else p
        return self._sample("choice", lambda rng: jax.random.choice(
            rng, a_val, size, replace=replace, p=pv), ctx)

    def shuffle(self, x):
        import jax

        from .. import random_state

        x._set_data(jax.random.permutation(random_state.next_key(), x.data))

    def permutation(self, x):
        import jax

        if isinstance(x, int):
            return self._sample(
                "permutation",
                lambda rng: jax.random.permutation(rng, x))
        return self._sample(
            "permutation",
            lambda rng: jax.random.permutation(rng, _data(x)))

    def seed(self, seed=None):
        from .. import random_state

        random_state.seed(seed)


random = _Random()





__all__ = sorted(
    [n for n in globals()
     if not n.startswith("_") and n not in ("builtins", "NDArray",
                                            "Context", "MXNetError",
                                            "current_context",
                                            "imperative_invoke")])


# ---------------------------------------------------------------------------
# np_* breadth (round 4, VERDICT r3 missing #6): the long tail of the
# reference's ``_np_*`` mirror. Three mechanical classes:
#
# * jnp-delegated — tape-aware via imperative_invoke; any NDArray in the
#   positional args becomes a traced operand, everything else is static.
# * host-fallback — data-DEPENDENT output shapes (nonzero, unique set ops,
#   compress...): XLA requires static shapes, so these compute on host
#   NumPy like the eager-only mx.nd ops do (reference kernels are also
#   sync points for these).
# * aliases / dtype re-exports — NumPy 2.x spellings and scalar types.
# ---------------------------------------------------------------------------


def _np_delegate(jname):
    def fn(*args, out=None, **kwargs):
        jnp = _jnp()
        jf = getattr(jnp, jname)
        # ANY NDArray operand — positional, keyword, or up to two levels
        # inside a positional list/tuple (select/column_stack/choose take
        # flat sequences; np.block takes nested [[A, B], [C, D]]) — must
        # ride the tape-aware invoke path, or autograd through it silently
        # drops (or jnp rejects the NDArray outright)
        tensors = []
        slots = []  # ("arg", i) | ("kw", k) | ("seq", i, j) | ("seq2", i, j, k)
        for i, a in enumerate(args):
            if isinstance(a, NDArray):
                slots.append(("arg", i))
                tensors.append(a)
            elif isinstance(a, (list, tuple)):
                for j, el in enumerate(a):
                    if isinstance(el, NDArray):
                        slots.append(("seq", i, j))
                        tensors.append(el)
                    elif isinstance(el, (list, tuple)):
                        for k2, el2 in enumerate(el):
                            if isinstance(el2, NDArray):
                                slots.append(("seq2", i, j, k2))
                                tensors.append(el2)
        for k, v in kwargs.items():
            if isinstance(v, NDArray):
                slots.append(("kw", k))
                tensors.append(v)
        static = list(args)

        def run(*ds):
            call = [[list(el) if isinstance(el, (list, tuple)) else el
                     for el in a] if isinstance(a, (list, tuple)) else a
                    for a in static]
            kw = dict(kwargs)
            for slot, d in zip(slots, ds):
                if slot[0] == "arg":
                    call[slot[1]] = d
                elif slot[0] == "seq":
                    call[slot[1]][slot[2]] = d
                elif slot[0] == "seq2":
                    call[slot[1]][slot[2]][slot[3]] = d
                else:
                    kw[slot[1]] = d
            res = jf(*call, **kw)
            # imperative_invoke multi-output handling covers tuple AND
            # list results, so no conversion is needed here
            return res

        return _invoke(f"np_{jname}", run, tensors, out=out)

    fn.__name__ = jname
    fn.__qualname__ = f"np.{jname}"
    fn.__doc__ = f"NumPy-semantics {jname} (delegates to jax.numpy)."
    return fn


_JNP_DELEGATED = [
    # unary math / elementwise
    "fabs", "fix", "positive", "signbit", "sinc", "i0", "nan_to_num",
    "spacing", "angle", "real", "imag", "conj", "conjugate", "deg2rad",
    "rad2deg", "exp2", "isneginf", "isposinf", "isreal", "iscomplex",
    "frexp", "modf", "invert", "round",
    # binary / ternary elementwise
    "float_power", "fmax", "fmin", "gcd", "lcm", "ldexp", "heaviside",
    "nextafter", "logaddexp", "logaddexp2", "divmod", "copysign",
    "bitwise_and", "bitwise_or", "bitwise_xor", "left_shift",
    "right_shift",
    # reductions / statistics
    "ptp", "count_nonzero", "average", "percentile", "quantile", "cov",
    "corrcoef", "nanmax", "nanmin", "nanargmax", "nanargmin", "nansum",
    "nanprod", "nancumsum", "nancumprod", "nanmean", "nanmedian",
    "nanstd", "nanvar", "nanpercentile", "nanquantile",
    # shape / rearrange
    "fliplr", "flipud", "rot90", "rollaxis", "resize", "pad", "trace",
    "diagonal", "diag", "diagflat", "tril", "triu", "kron", "cross",
    "convolve", "correlate", "append", "delete", "insert",
    "take_along_axis", "apply_along_axis", "apply_over_axes",
    "partition", "argpartition", "searchsorted", "digitize", "interp",
    "gradient", "diff", "ediff1d", "unwrap", "select", "choose",
    "bincount", "isin", "packbits", "unpackbits",
    # multi-array
    "column_stack", "block", "broadcast_arrays",
    # polynomials / windows
    "poly", "polyadd", "polyder", "polyfit", "polyint", "polymul",
    "polysub", "polyval", "roots", "vander", "bartlett", "blackman",
    "hamming", "hanning", "kaiser",
    # comparison
    "isclose", "array_equal", "array_equiv",
    # indexing helpers
    "unravel_index", "ravel_multi_index",
    # multi-output (imperative_invoke wraps tuple/list results itself)
    "dsplit", "hsplit", "vsplit", "histogram", "histogram2d",
    "histogramdd",
]
for _jname in _JNP_DELEGATED:
    if hasattr(_onp, _jname) and hasattr(__import__("jax.numpy",
                                                    fromlist=["x"]),
                                         _jname):
        if _jname not in globals():
            globals()[_jname] = _np_delegate(_jname)

def fill_diagonal(a, val, wrap=False):
    """In-place diagonal fill (NumPy mutates and returns None); routed
    through the NDArray write lens so views/tape stay consistent."""
    out = _invoke("np_fill_diagonal",
                  lambda d: _jnp().fill_diagonal(d, val, wrap=wrap,
                                                 inplace=False), [a])
    a[:] = out


def _np_host(oname):
    """Host NumPy fallback for data-dependent output shapes."""

    def fn(*args, **kwargs):
        of = getattr(_onp, oname)
        conv = [a.asnumpy() if isinstance(a, NDArray) else a for a in args]
        res = of(*conv, **kwargs)
        if isinstance(res, tuple):
            return tuple(array(r) if isinstance(r, _onp.ndarray) else r
                         for r in res)
        return array(res) if isinstance(res, _onp.ndarray) else res

    fn.__name__ = oname
    fn.__qualname__ = f"np.{oname}"
    fn.__doc__ = (f"NumPy-semantics {oname}. Output shape is data-"
                  "dependent, so this is an eager host op (sync point) — "
                  "the same contract as the reference's dynamic-shape "
                  "kernels.")
    return fn


for _oname in ["nonzero", "flatnonzero", "argwhere", "compress", "extract",
               "union1d", "intersect1d", "setdiff1d", "setxor1d", "in1d",
               "trim_zeros", "piecewise"]:
    if _oname not in globals():
        globals()[_oname] = _np_host(_oname)


def allclose(a, b, rtol=1e-05, atol=1e-08, equal_nan=False):
    av = a.asnumpy() if isinstance(a, NDArray) else a
    bv = b.asnumpy() if isinstance(b, NDArray) else b
    return builtins.bool(_onp.allclose(av, bv, rtol=rtol, atol=atol,
                                       equal_nan=equal_nan))


def histogram_bin_edges(a, bins=10, range=None, weights=None):
    return array(_onp.histogram_bin_edges(
        a.asnumpy() if isinstance(a, NDArray) else a, bins=bins,
        range=range, weights=weights))


# constructors
def identity(n, dtype=None, ctx=None):
    return array(_onp.identity(n, dtype=dtype or "float32"), ctx=ctx)


def tri(N, M=None, k=0, dtype=None, ctx=None):
    return array(_onp.tri(N, M=M, k=k, dtype=dtype or "float32"), ctx=ctx)


def logspace(start, stop, num=50, endpoint=True, base=10.0, dtype=None,
             ctx=None):
    return array(_onp.logspace(start, stop, num=num, endpoint=endpoint,
                               base=base, dtype=dtype), ctx=ctx)


def geomspace(start, stop, num=50, endpoint=True, dtype=None, ctx=None):
    return array(_onp.geomspace(start, stop, num=num, endpoint=endpoint,
                                dtype=dtype), ctx=ctx)


def empty_like(prototype, dtype=None, order="C", subok=True, shape=None):
    return _invoke("np_empty_like",
                   lambda d: _jnp().zeros(shape or d.shape,
                                          dtype or d.dtype), [prototype])


def fromfunction(function, shape, dtype=float, **kwargs):
    return array(_onp.fromfunction(function, shape, dtype=dtype, **kwargs))


def indices(dimensions, dtype=None, ctx=None):
    return array(_onp.indices(dimensions, dtype=dtype or "int64"), ctx=ctx)


def copy(a):
    return _invoke("np_copy", lambda d: _jnp().array(d), [a])


def astype(x, dtype, copy=True):
    return x.astype(dtype)


def unique_values(x):
    return array(_onp.unique(x.asnumpy() if isinstance(x, NDArray) else x))


# index-grid helpers (host-side tuples of index arrays)
def diag_indices(n, ndim=2):
    return tuple(array(i) for i in _onp.diag_indices(n, ndim))


def diag_indices_from(arr):
    return tuple(array(i) for i in _onp.diag_indices_from(arr.asnumpy()))


def tril_indices(n, k=0, m=None):
    return tuple(array(i) for i in _onp.tril_indices(n, k=k, m=m))


def triu_indices(n, k=0, m=None):
    return tuple(array(i) for i in _onp.triu_indices(n, k=k, m=m))


def tril_indices_from(arr, k=0):
    return tuple(array(i) for i in _onp.tril_indices_from(arr.asnumpy(), k=k))


def triu_indices_from(arr, k=0):
    return tuple(array(i) for i in _onp.triu_indices_from(arr.asnumpy(), k=k))


def mask_indices(n, mask_func, k=0):
    mf = {"tril": _onp.tril, "triu": _onp.triu}.get(mask_func, mask_func)
    return tuple(array(i) for i in _onp.mask_indices(n, mf, k))


def ix_(*args):
    return tuple(array(r) for r in _onp.ix_(
        *[a.asnumpy() if isinstance(a, NDArray) else a for a in args]))


def broadcast_shapes(*shapes):
    return _onp.broadcast_shapes(*shapes)


# dtype metadata (host delegates — reference re-exports numpy's)
finfo = _onp.finfo
iinfo = _onp.iinfo
result_type = _onp.result_type
promote_types = _onp.promote_types
can_cast = _onp.can_cast
issubdtype = _onp.issubdtype


def isscalar(element):
    return _onp.isscalar(element) or (
        isinstance(element, NDArray) and element.ndim == 0)


def iterable(y):
    try:
        iter(y)
        return True
    except TypeError:
        return False


def size(a, axis=None):
    if axis is None:
        n = 1
        for d in a.shape:
            n *= d
        return n
    return a.shape[axis]


def isrealobj(x):
    return not iscomplexobj(x)


def iscomplexobj(x):
    dt = getattr(x, "dtype", None)
    return dt is not None and _onp.issubdtype(_onp.dtype(str(dt)),
                                              _onp.complexfloating)


# NumPy 2.x spellings + long-tail aliases
acos, acosh = globals()["arccos"], globals()["arccosh"]
asin, asinh = globals()["arcsin"], globals()["arcsinh"]
atan, atanh = globals()["arctan"], globals()["arctanh"]
atan2 = globals()["arctan2"]
concat = globals()["concatenate"]
permute_dims = globals()["transpose"]
pow = globals()["power"]
bitwise_not = bitwise_invert = invert
row_stack = vstack
around = round
trapz = trapezoid = _np_delegate("trapezoid") \
    if hasattr(__import__("jax.numpy", fromlist=["x"]), "trapezoid") \
    else _np_host("trapz")
real_if_close = _np_delegate("real_if_close") \
    if hasattr(__import__("jax.numpy", fromlist=["x"]), "real_if_close") \
    else _np_host("real_if_close")
matrix_transpose = _np_delegate("matrix_transpose")
cumprod = _np_delegate("cumprod")
ravel = _np_delegate("ravel")
vecdot = (_np_delegate("vecdot")
          if hasattr(__import__("jax.numpy", fromlist=["x"]), "vecdot")
          else None)
if vecdot is None:
    del vecdot

# scalar-type re-exports (reference: mx.np re-exports numpy scalar types)
uint16, uint32, uint64 = _onp.uint16, _onp.uint32, _onp.uint64
intc, int_, longlong, intp = _onp.intc, _onp.int_, _onp.longlong, _onp.intp
uintc, uint, ulonglong = _onp.uintc, _onp.uint, _onp.ulonglong
byte, short, ubyte, ushort = _onp.byte, _onp.short, _onp.ubyte, _onp.ushort
half, single, double = _onp.half, _onp.single, _onp.double
complex64, complex128 = _onp.complex64, _onp.complex128
csingle, cdouble = _onp.csingle, _onp.cdouble
floating, integer, number = _onp.floating, _onp.integer, _onp.number
inexact, signedinteger = _onp.inexact, _onp.signedinteger
unsignedinteger, character = _onp.unsignedinteger, _onp.character
generic, flexible = _onp.generic, _onp.flexible
bool = _onp.bool_



# ---------------------------------------------------------------------------
# index-expression helpers (reference: numpy.lib.index_tricks — mx.np
# mirrors the numpy surface, SURVEY.md §2.3 numpy API row)
# ---------------------------------------------------------------------------


def _slice_to_axis(sl):
    """slice -> 1-D coordinate array, numpy index-trick conventions:
    an IMAGINARY step means linspace point count (``1:2:5j``)."""
    start = 0 if sl.start is None else sl.start
    if isinstance(sl.step, complex):
        return linspace(start, sl.stop, int(abs(sl.step)))
    return arange(start, sl.stop, 1 if sl.step is None else sl.step)


class _MGridClass:
    """``mgrid[...]``: dense coordinate grids (``ogrid`` = sparse)."""

    def __init__(self, sparse):
        self._sparse = sparse

    def __getitem__(self, key):
        slices = key if isinstance(key, tuple) else (key,)
        axes = [_slice_to_axis(sl) for sl in slices]
        if len(axes) == 1:
            return axes[0]
        if self._sparse:
            out = []
            for i, ax in enumerate(axes):
                shp = [1] * len(axes)
                shp[i] = ax.shape[0]
                out.append(ax.reshape(tuple(shp)))
            return out
        grids = meshgrid(*axes, indexing="ij")
        return stack(grids, axis=0)


mgrid = _MGridClass(sparse=False)
ogrid = _MGridClass(sparse=True)


class _RClass:
    """``r_[...]``: concatenate slices/arrays/scalars along axis 0."""

    _axis = 0

    def __getitem__(self, key):
        items = key if isinstance(key, tuple) else (key,)
        if items and isinstance(items[0], str):
            raise NotImplementedError(
                "np.r_/np.c_ string directives ('2,0', 'r') are not "
                "supported; pass arrays/slices")
        parts = []
        for it in items:
            if isinstance(it, slice):
                parts.append(_slice_to_axis(it))
            else:
                parts.append(atleast_1d(asarray(it)))
        if self._axis != 0:
            parts = [p.reshape((-1, 1)) if p.ndim == 1 else p
                     for p in parts]
        return concatenate(parts, axis=self._axis)


class _CClass(_RClass):
    """``c_[...]``: column-wise concatenation (1-D inputs become
    columns)."""

    _axis = -1


r_ = _RClass()
c_ = _CClass()


__all__ = sorted(
    [n for n in globals()
     if not n.startswith("_") and n not in ("builtins", "NDArray",
                                            "Context", "MXNetError",
                                            "current_context",
                                            "imperative_invoke")])
